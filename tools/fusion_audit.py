#!/usr/bin/env python
"""Audit XLA fusion and live-buffer pressure of a compiled train step.

Usage::

    python tools/fusion_audit.py --dump out.json [--model mlp|transformer]
                                 [--batch N] [--seq T] [--attn-impl X]
    python tools/fusion_audit.py out.json [...]      # pretty-print dumps
    python tools/fusion_audit.py step.hlo.txt        # parse a raw HLO dump
    python tools/fusion_audit.py --diff old.json new.json

``--dump`` compiles one fused train step AOT (no execution), walks the
*optimized* HLO, and writes a JSON artifact: ``memory_analysis()``
totals (temp/argument/output/generated-code bytes — temp is the peak
live-buffer watermark the ``attn_peak_bytes`` bench column reports),
per-opcode instruction counts, the collective roster (is the gradient
reduction bucketed? did it stay one step-ending all-reduce?), and the
largest **unfused top-level producers** — entry-computation ops that are
not fusions, each one a separate kernel launch and a materialized
buffer.  That ranking is where an O(T²) attention score matrix or a
missed transpose fold shows up by name.

``--diff`` compares two dumps — run one before and one after a kernel
change (e.g. ``MXNET_ATTN_IMPL=reference`` vs ``flash``) and the report
shows the temp-bytes delta, opcode-count drift, and which big buffers
appeared/vanished.

Reading/diffing dumps is stdlib-only (like ``tools/compile_report.py``):
the artifact outlives the training venv.  ``--dump`` imports mxnet_tpu.
"""
import argparse
import json
import os
import re
import sys
import time

ARTIFACT_KIND = "mxnet_tpu-fusion-audit"
TOP_N = 12

_BYTES = {"pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2,
          "bf16": 2, "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8,
          "f64": 8, "c64": 8, "c128": 16}

# `  %name = f32[8,128]{1,0} opcode(...)` (entry or nested computation)
_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?([\w.\-]+)\s*=\s*"
    r"\(?([a-z]+\d*)\[([\d,]*)\][^\s]*\s+([\w\-]+)\(")
# `%fused_computation.3 (param_0.7: f32[...]) -> f32[...] {`
_COMP_RE = re.compile(r"^(ENTRY\s+)?%?([\w.\-]+)\s+\([^)]*\)\s*->.*{")

# top-level ops that are bookkeeping, not kernels
_SKIP_OPS = {"parameter", "constant", "get-tuple-element", "tuple",
             "bitcast", "after-all", "partition-id", "replica-id"}
_COLLECTIVES = ("all-reduce", "all-gather", "reduce-scatter",
                "all-to-all", "collective-permute")
# `replica_groups={{0,2,4,6},{1,3,5,7}}` (literal) or the iota form
# `replica_groups=[2,4]<=[4,2]T(1,0)` ([num_groups, group_size])
_RG_LITERAL_RE = re.compile(r"replica_groups=\{(\{[^}]*\}(?:,\{[^}]*\})*)\}")
_RG_IOTA_RE = re.compile(r"replica_groups=\[(\d+),(\d+)\]<=")


def _replica_groups(line):
    """(num_groups, group_size) of a collective's replica_groups clause,
    or (None, None) when absent.  One group spanning everything = a
    GLOBAL collective; several groups = group-scoped (the composed
    plan's signature)."""
    m = _RG_IOTA_RE.search(line)
    if m:
        return int(m.group(1)), int(m.group(2))
    m = _RG_LITERAL_RE.search(line)
    if not m:
        return None, None
    groups = m.group(1).split("},{")
    size = len([t for t in groups[0].strip("{}").split(",") if t.strip()])
    return len(groups), size


def _collective_kind(op):
    """Normalize an HLO collective opcode to its base kind: the async
    pairs (``all-reduce-start``/``-done``, ``all-gather-start``, …)
    count as their base collective, so a psum → reduce-scatter swap in
    the step program reads as exactly that in the roster and in
    ``--diff`` — not as an opaque opcode shuffle."""
    for kind in _COLLECTIVES:
        if op == kind or op.startswith(kind + "-"):
            return kind
    return op


def _kind_summary(payload):
    """Per-kind {count, bytes} roster; derived from the raw collective
    list so pre-existing artifacts diff fine.  ``-done`` halves of async
    pairs are skipped to avoid double-counting one collective."""
    kinds = {}
    for c in payload.get("collectives") or []:
        if c["op"].endswith("-done"):
            continue
        k = _collective_kind(c["op"])
        ent = kinds.setdefault(k, {"count": 0, "bytes": 0})
        ent["count"] += 1
        ent["bytes"] += int(c.get("bytes") or 0)
    return kinds


def expect_async(payload, path):
    """Overlap-capability probe.  On a backend that lowers collectives
    to ``-start``/``-done`` pairs (TPU/GPU), every collective that is
    NOT such a pair serializes the stream and is reported as a named
    offender.  CPU XLA lowers every collective synchronously, so there
    the probe falls back to a structural check: a ``zero_stage == 3``
    dump with more than one gather bucket must not contain a monolithic
    all-gather moving the whole sharded-parameter footprint at once —
    that is the step-ending full gather the bucketed schedule exists to
    eliminate.  Returns True on pass."""
    colls = payload.get("collectives") or []
    if not colls:
        print("EXPECT-ASYNC %s: PASS (no collectives in the entry "
              "computation)" % path)
        return True
    has_async = any(c["op"].endswith("-start") for c in colls)
    offenders = []
    if has_async:
        note = ("async-capable backend (-start/-done pairs present); "
                "sync collectives are offenders")
        for c in colls:
            if c["op"].endswith(("-start", "-done")):
                continue
            offenders.append("%s (%s, %s)"
                             % (c["name"], c["op"],
                                _fmt_bytes(c["bytes"])))
    else:
        note = ("backend emits synchronous collectives only (CPU-style "
                "lowering); structural check on the gather schedule")
        total = int(payload.get("zero_sharded_bytes") or 0)
        buckets = int(payload.get("zero_gather_buckets") or 0)
        if payload.get("zero_stage") == 3 and buckets > 1 and total:
            for c in colls:
                if _collective_kind(c["op"]) != "all-gather":
                    continue
                if int(c.get("bytes") or 0) >= total:
                    offenders.append(
                        "%s (%s, %s >= %s sharded footprint: "
                        "monolithic full-parameter gather)"
                        % (c["name"], c["op"], _fmt_bytes(c["bytes"]),
                           _fmt_bytes(total)))
    if offenders:
        print("EXPECT-ASYNC %s: FAIL (%s)" % (path, note))
        for o in offenders:
            print("    offender: %s" % o)
        return False
    print("EXPECT-ASYNC %s: PASS (%s)" % (path, note))
    return True


def expect_plan(payload, path):
    """Composed-plan collective roster check.

    A ``--plan data=D,model=M`` dump must show GROUP-SCOPED collectives:
    the ZeRO reduce-scatter/all-gather runs over the data axis *within*
    each model group (M groups of D devices), and the Megatron
    activation reductions run over the model axis within each data
    group (D groups of M devices).  A single-group collective spanning
    the whole mesh while moving the sharded-parameter footprint is the
    monolithic global gather/reduce the plan exists to eliminate —
    named offender, FAIL with the roster printed.  Scalar global psums
    (loss, grad-norm) are exact-by-construction and legitimate.
    Returns True on pass."""
    plan = payload.get("plan") or {}
    model_n = int(plan.get("model") or 1)
    data_n = int(plan.get("data") or 1)
    colls = [c for c in (payload.get("collectives") or [])
             if not c["op"].endswith("-done")]
    sized = [c for c in colls if c.get("groups")]
    total = int(payload.get("zero_sharded_bytes") or 0)

    def _is(c, kind):
        return _collective_kind(c["op"]) == kind

    failures = []
    if model_n > 1:
        # data-scoped ZeRO traffic: M groups (one per model shard)
        if not any((_is(c, "reduce-scatter") or _is(c, "all-gather"))
                   and c["groups"] == model_n for c in sized):
            failures.append(
                "no group-scoped reduce-scatter/all-gather with "
                "%d replica groups (data-axis ZeRO traffic should be "
                "scoped per model group)" % model_n)
        # model-scoped TP reductions: D groups (one per data shard)
        if not any(_is(c, "all-reduce") and c["groups"] == data_n
                   for c in sized):
            failures.append(
                "no group-scoped all-reduce with %d replica groups "
                "(Megatron activation reduction should be scoped per "
                "data group)" % data_n)
    offenders = []
    for c in sized:
        if c["groups"] != 1 or _collective_kind(c["op"]) not in (
                "all-reduce", "all-gather", "reduce-scatter"):
            continue
        if total and int(c.get("bytes") or 0) >= total:
            offenders.append(
                "%s (%s, %s >= %s sharded footprint: global monolithic "
                "collective across the whole mesh)"
                % (c["name"], c["op"], _fmt_bytes(c["bytes"]),
                   _fmt_bytes(total)))
    if failures or offenders:
        print("EXPECT-PLAN %s: FAIL (plan %s)" % (path, plan))
        for f in failures:
            print("    missing: %s" % f)
        for o in offenders:
            print("    offender: %s" % o)
        print("    roster:")
        for c in colls:
            print("      %-40s %-20s groups=%-4s %s"
                  % (c["name"], c["op"], c.get("groups"),
                     _fmt_bytes(c["bytes"])))
        return False
    print("EXPECT-PLAN %s: PASS (plan %s: ZeRO traffic scoped to %d "
          "model group(s), TP reductions scoped to %d data group(s), "
          "no global monolithic collective)"
          % (path, plan, model_n, data_n))
    return True


def expect_fp8(payload, path):
    """fp8 lowering check for a ``--fp8`` dump.

    The fp8 route emits each operand as a quantize-dequantize pair; XLA
    must fold those into the surrounding fusions (into a real fp8
    operand on native hardware).  A standalone ``convert`` among the
    largest top-level producers is a pair that ESCAPED — a full
    activation copy materialized per matmul operand — and a named
    offender.  The temp-bytes watermark vs the bf16 baseline compiled
    alongside (``baseline_memory``) is bounded at 1.25x on fp8-native
    backends (TPU/GPU), where the saved matmul residuals really are
    1-byte codes; on CPU the residuals stay f32 (fake-cast numerics
    only), so the delta is reported but advisory there.  Returns True
    on pass."""
    if not payload.get("fp8"):
        print("EXPECT-FP8 %s: FAIL (artifact was not dumped with --fp8; "
              "nothing to audit)" % path)
        return False
    failures = []
    offenders = ["%s (%s, %s)" % (p["name"], p["shape"],
                                  _fmt_bytes(p["bytes"]))
                 for p in payload.get("unfused_producers") or []
                 if p["op"] == "convert"]
    if offenders:
        failures.append("standalone convert among the largest top-level "
                        "producers (escaped quantize-dequantize pair)")
    base = (payload.get("baseline_memory") or {}).get("temp_size")
    cur = (payload.get("memory") or {}).get("temp_size")
    native = payload.get("backend") in ("tpu", "gpu")
    ratio = None
    if base and cur:
        ratio = float(cur) / float(base)
        if ratio > 1.25 and native:
            failures.append("temp bytes %s vs bf16 baseline %s "
                            "(%.2fx > 1.25x)" % (_fmt_bytes(cur),
                                                 _fmt_bytes(base), ratio))
    if failures:
        print("EXPECT-FP8 %s: FAIL" % path)
        for f in failures:
            print("    %s" % f)
        for o in offenders:
            print("    offender: %s" % o)
        return False
    note = "" if ratio is None else \
        ", temp bytes %.2fx of bf16 baseline%s" % (
            ratio, "" if native else " (advisory: f32 residuals on "
            "this backend)")
    print("EXPECT-FP8 %s: PASS (no standalone convert in the top "
          "producers%s)" % (path, note))
    return True


def _shape_bytes(dtype, dims):
    n = _BYTES.get(dtype, 4)
    for d in dims.split(","):
        if d:
            n *= int(d)
    return n


def parse_hlo(text):
    """Walk optimized HLO text: per-opcode counts, collectives, and the
    largest unfused entry-computation producers."""
    op_counts = {}
    collectives = []
    producers = []
    in_entry = False
    for line in text.splitlines():
        mc = _COMP_RE.match(line.strip()) if "{" in line else None
        if mc:
            in_entry = bool(mc.group(1))
            continue
        m = _INSTR_RE.match(line)
        if not m:
            continue
        name, dtype, dims, op = m.groups()
        op_counts[op] = op_counts.get(op, 0) + 1
        if not in_entry:
            continue
        nbytes = _shape_bytes(dtype, dims)
        if any(op.startswith(c) for c in _COLLECTIVES):
            ngroups, gsize = _replica_groups(line)
            collectives.append({"name": name, "op": op, "bytes": nbytes,
                                "groups": ngroups, "group_size": gsize})
        if op in _SKIP_OPS or op.startswith("fusion"):
            continue
        producers.append({"name": name, "op": op,
                          "shape": "%s[%s]" % (dtype, dims),
                          "bytes": nbytes})
    producers.sort(key=lambda p: -p["bytes"])
    return {"op_counts": op_counts,
            "collectives": collectives,
            "unfused_producers": producers[:TOP_N],
            "unfused_producer_count": len(producers)}


def _fmt_bytes(n):
    try:
        n = float(n)
    except (TypeError, ValueError):
        return repr(n)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if n < 1024 or unit == "GiB":
            return "%.1f %s" % (n, unit)
        n /= 1024.0


def dump(out_path, model="transformer", batch=None, seq=None,
         attn_impl=None, mesh=None, zero=None, check_async=False,
         plan=None, check_plan=False, fp8=None, check_fp8=False):
    """Compile one fused train step AOT and write the audit artifact.

    ``mesh=N`` compiles over an N-way data mesh so the gradient
    collectives exist at all; dump once with ``--zero off`` and once
    with ``--zero on`` and ``--diff`` the two to see the step's
    all-reduce turn into a reduce-scatter + all-gather pair.  A
    ``--zero 3`` dump against a ``--zero on`` one shows the trailing
    full-parameter all-gather replaced by the in-step bucket
    gathers.  ``plan="data=4,model=2"`` compiles the COMPOSED step
    (``TrainStep(plan=...)``) and records the plan identity so
    ``--expect-plan`` can audit the roster: group-scoped collectives
    only, no monolithic global gather/reduce.  ``fp8="on"`` compiles
    under ``MXNET_FP8`` at bf16 compute and ALSO compiles the matching
    bf16 step without fp8, recording its memory as
    ``baseline_memory`` so ``--expect-fp8`` can bound the temp-bytes
    delta."""
    if attn_impl:
        os.environ["MXNET_ATTN_IMPL"] = attn_impl
    if fp8:
        os.environ["MXNET_FP8"] = fp8
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import mxnet_tpu as mx
    from mxnet_tpu.fused import TrainStep

    if model == "mlp":
        net = mx.sym.Variable("data")
        net = mx.sym.FullyConnected(net, num_hidden=1024, name="fc1")
        net = mx.sym.Activation(net, act_type="relu")
        net = mx.sym.FullyConnected(net, num_hidden=10, name="fc2")
        sym = mx.sym.SoftmaxOutput(net, name="softmax")
        shapes = {"data": (batch or 64, 512),
                  "softmax_label": (batch or 64,)}
    else:
        from mxnet_tpu.models import transformer

        cfg = dict(vocab_size=8192, num_layers=2, d_model=256,
                   num_heads=4, seq_len=seq or 512)
        sym = transformer.get_symbol(**cfg)
        b = batch or 2
        shapes = {"data": (b, cfg["seq_len"]),
                  "softmax_label": (b, cfg["seq_len"])}

    dev_mesh = None
    plan_obj = None
    if plan:
        from mxnet_tpu.parallel import ParallelPlan

        plan_obj = ParallelPlan.parse(plan)
        if zero is not None and plan_obj.zero is None:
            plan_obj = ParallelPlan(data=plan_obj.data,
                                    model=plan_obj.model,
                                    pipe=plan_obj.pipe, seq=plan_obj.seq,
                                    zero=zero,
                                    schedule=plan_obj.schedule,
                                    n_microbatches=plan_obj.n_microbatches)
    elif mesh:
        from mxnet_tpu.parallel import create_mesh

        dev_mesh = create_mesh({"data": int(mesh)})
    step = TrainStep(sym, optimizer="sgd",
                     optimizer_params={"learning_rate": 0.01},
                     mesh=dev_mesh, zero=None if plan_obj else zero,
                     plan=plan_obj,
                     compute_dtype="bfloat16" if fp8 else None)
    step.compile(shapes)
    compiled = step._aot
    import jax

    payload = {"kind": ARTIFACT_KIND, "pid": os.getpid(),
               "time": time.time(), "model": model,
               "backend": jax.default_backend(), "shapes":
               {k: list(v) for k, v in shapes.items()},
               "mesh": int(mesh) if mesh else None,
               "zero": step.zero_axis is not None,
               "zero_stage": (0 if step.zero_axis is None
                              else 3 if getattr(step, "zero3", False)
                              else 1),
               "attn_impl": attn_impl or os.environ.get(
                   "MXNET_ATTN_IMPL", "auto")}
    if plan_obj is not None:
        payload["plan"] = plan_obj.describe()
        shape = dict(step.mesh.shape)
        # resolve the data=-1 wildcard: the audit reads group counts
        payload["plan"]["data"] = int(shape.get("data", 1))
        payload["mesh_axes"] = {k: int(v) for k, v in shape.items()}
        payload["plan_fingerprint"] = plan_obj.fingerprint(step.mesh)
    lay = getattr(step, "_zero_lay", None)
    if lay:
        from mxnet_tpu.parallel import overlap as _ov
        from mxnet_tpu.parallel import zero as _z

        sharded = {n: e for n, e in lay.items() if e.sharded}
        sizes = {n: e.padded * e.dtype.itemsize
                 for n, e in sharded.items()}
        payload["zero_sharded_bytes"] = sum(sizes.values())
        if payload["zero_stage"] == 3 and sharded:
            payload["zero_gather_buckets"] = len(_ov.bucket_partition(
                list(sharded), sizes, _z.gather_bucket_bytes()))
    try:
        mem = compiled.memory_analysis()
        payload["memory"] = {
            k: int(getattr(mem, k + "_in_bytes", 0) or 0)
            for k in ("temp_size", "argument_size", "output_size",
                      "generated_code_size")}
    except Exception as e:  # backend without memory_analysis
        payload["memory"] = {"error": str(e)}
    payload.update(parse_hlo(compiled.as_text()))
    if fp8:
        # the matching bf16 step, fp8 off: its watermark is the
        # --expect-fp8 temp-bytes reference
        payload["fp8"] = fp8
        os.environ["MXNET_FP8"] = "off"
        try:
            base = TrainStep(sym, optimizer="sgd",
                             optimizer_params={"learning_rate": 0.01},
                             mesh=dev_mesh,
                             zero=None if plan_obj else zero,
                             plan=plan_obj, compute_dtype="bfloat16")
            base.compile(shapes)
            bmem = base._aot.memory_analysis()
            payload["baseline_memory"] = {
                k: int(getattr(bmem, k + "_in_bytes", 0) or 0)
                for k in ("temp_size", "argument_size", "output_size",
                          "generated_code_size")}
        except Exception as e:  # mxlint: disable=MX008
            # best-effort reference: a baseline that cannot compile
            # degrades --expect-fp8's temp-bytes bound to advisory
            payload["baseline_memory"] = {"error": str(e)}
        finally:
            os.environ["MXNET_FP8"] = fp8
    with open(out_path, "w") as f:
        json.dump(payload, f)
    print("wrote %s" % out_path)
    print_report(out_path, payload)
    rc = 0
    if check_async and not expect_async(payload, out_path):
        rc = 1
    if check_plan and not expect_plan(payload, out_path):
        rc = 1
    if check_fp8 and not expect_fp8(payload, out_path):
        rc = 1
    return rc


def print_report(path, payload):
    print("=" * 72)
    print("FUSION AUDIT  %s" % path)
    if payload.get("model"):
        print("  model %s  shapes %s  attn_impl %s"
              % (payload["model"], payload.get("shapes"),
                 payload.get("attn_impl")))
    mem = payload.get("memory") or {}
    if mem and "error" not in mem:
        print("  memory (memory_analysis):")
        for k in ("temp_size", "argument_size", "output_size",
                  "generated_code_size"):
            note = "  <-- peak live-buffer watermark" \
                if k == "temp_size" else ""
            print("    %-20s %12s%s" % (k, _fmt_bytes(mem.get(k, 0)),
                                        note))
    counts = payload.get("op_counts") or {}
    fused = counts.get("fusion", 0)
    total = sum(counts.values())
    print("  instructions: %d total, %d fusions" % (total, fused))
    top_ops = sorted(counts.items(), key=lambda kv: -kv[1])[:8]
    print("    " + "  ".join("%s:%d" % kv for kv in top_ops))
    colls = payload.get("collectives") or []
    kinds = _kind_summary(payload)
    print("  collectives: %d%s" % (
        len(colls),
        "" if not kinds else "  (" + ", ".join(
            "%s:%d" % (k, kinds[k]["count"])
            for k in sorted(kinds)) + ")"))
    for k in sorted(kinds):
        print("    %-28s x%-4d %s" % (k, kinds[k]["count"],
                                      _fmt_bytes(kinds[k]["bytes"])))
    for c in colls[:TOP_N]:
        print("    %-44s %-24s %s" % (c["name"], c["op"],
                                      _fmt_bytes(c["bytes"])))
    prods = payload.get("unfused_producers") or []
    print("  largest unfused top-level producers "
          "(%d total, top %d):" % (payload.get("unfused_producer_count",
                                               len(prods)), len(prods)))
    for p in prods:
        print("    %-44s %-16s %-20s %s"
              % (p["name"], p["op"], p["shape"], _fmt_bytes(p["bytes"])))


def diff(path_a, path_b):
    a, b = (_load(p) for p in (path_a, path_b))
    print("=" * 72)
    print("FUSION AUDIT DIFF  %s -> %s" % (path_a, path_b))
    ma, mb = a.get("memory") or {}, b.get("memory") or {}
    for k in ("temp_size", "argument_size", "output_size"):
        if k in ma and k in mb:
            va, vb = ma[k], mb[k]
            pct = " (%+.1f%%)" % (100.0 * (vb - va) / va) if va else ""
            print("  %-20s %12s -> %12s%s"
                  % (k, _fmt_bytes(va), _fmt_bytes(vb), pct))
    za, zb = a.get("zero_stage"), b.get("zero_stage")
    if za is not None and zb is not None and za != zb:
        def _ag_bytes(p):
            return sum(int(c.get("bytes") or 0)
                       for c in p.get("collectives") or []
                       if _collective_kind(c["op"]) == "all-gather"
                       and not c["op"].endswith("-done"))

        aga, agb = _ag_bytes(a), _ag_bytes(b)
        note = ""
        if zb == 3 and za in (1, True) and agb < aga:
            note = "  <-- trailing full-parameter all-gather gone " \
                   "(bucketed in-step gathers remain)"
        print("  zero stage %s -> %s: all-gather traffic %s -> %s%s"
              % (za, zb, _fmt_bytes(aga), _fmt_bytes(agb), note))
    ka, kb = _kind_summary(a), _kind_summary(b)
    kmoved = [(k, ka.get(k, {}).get("count", 0),
               kb.get(k, {}).get("count", 0),
               ka.get(k, {}).get("bytes", 0),
               kb.get(k, {}).get("bytes", 0))
              for k in sorted(set(ka) | set(kb))]
    print("  collective drift (by kind, new minus old):")
    if not any(na != nb or ba != bb for _, na, nb, ba, bb in kmoved):
        print("    (identical collective mix)")
    for k, na, nb, ba, bb in kmoved:
        if na == nb and ba == bb:
            continue
        print("    %-28s x%d -> x%d   %s -> %s"
              % (k, na, nb, _fmt_bytes(ba), _fmt_bytes(bb)))
    ca, cb = a.get("op_counts") or {}, b.get("op_counts") or {}
    drift = {op: cb.get(op, 0) - ca.get(op, 0)
             for op in set(ca) | set(cb)}
    moved = sorted((kv for kv in drift.items() if kv[1]),
                   key=lambda kv: -abs(kv[1]))
    print("  opcode drift (new minus old):")
    if not moved:
        print("    (identical opcode mix)")
    for op, d in moved[:TOP_N]:
        print("    %-28s %+d" % (op, d))
    # key by (op, shape), not instruction name — HLO renumbers every
    # instruction between compiles, shapes are the stable identity
    def by_sig(payload):
        sig = {}
        for p in payload.get("unfused_producers") or []:
            sig.setdefault((p["op"], p["shape"]), p)
        return sig

    pa, pb = by_sig(a), by_sig(b)
    for title, only, src in (("big buffers gone", set(pa) - set(pb), pa),
                             ("big buffers new", set(pb) - set(pa), pb)):
        print("  %s:" % title)
        if not only:
            print("    (none)")
        for key in sorted(only, key=lambda k: -src[k]["bytes"]):
            p = src[key]
            print("    %-16s %-20s %s" % (p["op"], p["shape"],
                                          _fmt_bytes(p["bytes"])))
    return 0


def _load(path):
    with open(path) as f:
        payload = json.load(f)
    if not isinstance(payload, dict) or \
            payload.get("kind") != ARTIFACT_KIND:
        raise SystemExit("%s: not a fusion-audit artifact" % path)
    return payload


def report_file(path):
    """JSON artifact or raw HLO text — detect and report either."""
    try:
        payload = _load(path)
    except (ValueError, SystemExit):
        with open(path) as f:
            text = f.read()
        if "HloModule" not in text:
            print("%s: neither a fusion-audit artifact nor HLO text"
                  % path, file=sys.stderr)
            return False
        print_report(path, parse_hlo(text))
        return True
    print_report(path, payload)
    return True


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="audit XLA fusion / live buffers of the fused step")
    ap.add_argument("paths", nargs="*",
                    help="fusion-audit JSON artifacts or raw HLO dumps")
    ap.add_argument("--dump", metavar="OUT",
                    help="compile a step and write an artifact "
                         "(imports mxnet_tpu)")
    ap.add_argument("--model", default="transformer",
                    choices=("transformer", "mlp"))
    ap.add_argument("--batch", type=int)
    ap.add_argument("--seq", type=int)
    ap.add_argument("--attn-impl",
                    help="force MXNET_ATTN_IMPL for the dump "
                         "(flash|reference|auto)")
    ap.add_argument("--mesh", type=int,
                    help="compile the dump over an N-way data mesh "
                         "(the gradient collectives only exist then)")
    ap.add_argument("--plan",
                    help="compile the COMPOSED step over a ParallelPlan "
                         "spec (e.g. data=4,model=2,zero=3); replaces "
                         "--mesh")
    ap.add_argument("--zero", choices=("auto", "on", "off", "3"),
                    help="MXNET_ZERO mode for the dump; diff a "
                         "--zero off dump against a --zero on one to "
                         "see the all-reduce -> reduce-scatter + "
                         "all-gather swap, or --zero on vs --zero 3 "
                         "to see the trailing full all-gather go")
    ap.add_argument("--expect-async", action="store_true",
                    help="fail (exit 1) when the step's collectives "
                         "are not overlap-capable: on backends that "
                         "emit async pairs, any sync collective is a "
                         "named offender; on sync-only backends (CPU) "
                         "a structural check rejects a monolithic "
                         "full-parameter all-gather under zero=3")
    ap.add_argument("--fp8", choices=("on", "auto"),
                    help="compile the dump under MXNET_FP8 at bf16 "
                         "compute, plus a matching bf16 baseline whose "
                         "memory lands in the artifact as "
                         "baseline_memory")
    ap.add_argument("--expect-fp8", action="store_true",
                    help="fail (exit 1) when an --fp8 dump shows a "
                         "standalone convert among the largest "
                         "top-level producers (an escaped "
                         "quantize-dequantize pair) or a temp-bytes "
                         "watermark above 1.25x the bf16 baseline")
    ap.add_argument("--expect-plan", action="store_true",
                    help="fail (exit 1) when a --plan dump's collective "
                         "roster is not group-scoped: ZeRO traffic must "
                         "run in per-model-group replica groups, TP "
                         "reductions in per-data-group ones, and no "
                         "global monolithic all-reduce/all-gather/"
                         "reduce-scatter may span the whole mesh")
    ap.add_argument("--diff", nargs=2, metavar=("OLD", "NEW"),
                    help="compare two artifacts")
    args = ap.parse_args(argv)
    if args.dump:
        return dump(args.dump, model=args.model, batch=args.batch,
                    seq=args.seq, attn_impl=args.attn_impl,
                    mesh=args.mesh, zero=args.zero,
                    check_async=args.expect_async,
                    plan=args.plan, check_plan=args.expect_plan,
                    fp8=args.fp8, check_fp8=args.expect_fp8)
    if args.diff:
        return diff(*args.diff)
    if not args.paths:
        ap.error("nothing to do: pass artifacts, --dump, or --diff")
    ok, async_fail = 0, 0
    for path in args.paths:
        ok += report_file(path)
        if args.expect_async or args.expect_plan or args.expect_fp8:
            try:
                payload = _load(path)
            except (ValueError, SystemExit):
                continue  # raw HLO text: no structural metadata
            if args.expect_async and not expect_async(payload, path):
                async_fail += 1
            if args.expect_plan and not expect_plan(payload, path):
                async_fail += 1
            if args.expect_fp8 and not expect_fp8(payload, path):
                async_fail += 1
    return 0 if ok and not async_fail else 1


if __name__ == "__main__":
    sys.exit(main())
