#!/usr/bin/env python
"""Generate the operator API reference from the registry (the reference
builds its docs/api pages from the same registry that generates the
frontends; docs/mxdoc.py).

    python tools/gen_api_docs.py [--out docs/api]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--out", default=os.path.join(
        os.path.dirname(__file__), "..", "docs", "api"))
    args = p.parse_args()

    from mxnet_tpu.ops import registry
    from mxnet_tpu.ops.op_names import INPUT_NAMES

    os.makedirs(args.out, exist_ok=True)
    seen = {}
    for name in registry.list_ops():
        op = registry.get(name)
        seen.setdefault(id(op), (op, []))[1].append(name)

    groups = {"nn": [], "tensor": [], "contrib": [], "optimizer": [],
              "random": [], "internal": []}
    for op, names in seen.values():
        primary = op.name
        if primary.startswith("_contrib_"):
            key = "contrib"
        elif primary.endswith("_update"):
            key = "optimizer"
        elif primary.startswith(("random_", "sample_", "_random")):
            key = "random"
        elif primary in INPUT_NAMES or primary[:1].isupper():
            key = "nn"
        elif primary.startswith("_"):
            key = "internal"
        else:
            key = "tensor"
        groups[key].append((primary, sorted(set(names) - {primary}), op))

    index = ["# Operator API reference",
             "",
             "Generated from the op registry by `tools/gen_api_docs.py` "
             "— the same registry that generates the `mx.nd.*` and "
             "`mx.sym.*` frontends.", ""]
    for key in ("nn", "tensor", "contrib", "random", "optimizer",
                "internal"):
        ops = sorted(groups[key])
        if not ops:
            continue
        lines = ["# %s operators" % key, ""]
        index.append("- [%s](%s.md) — %d ops" % (key, key, len(ops)))
        for primary, aliases, op in ops:
            lines.append("## `%s`" % primary)
            if aliases:
                lines.append("*aliases: %s*" %
                             ", ".join("`%s`" % a for a in aliases))
            lines.append("")
            lines.append(op.describe())
            lines.append("")
        with open(os.path.join(args.out, key + ".md"), "w") as f:
            f.write("\n".join(lines))
    with open(os.path.join(args.out, "index.md"), "w") as f:
        f.write("\n".join(index) + "\n")
    total = sum(len(v) for v in groups.values())
    print("wrote %d ops across %d pages to %s"
          % (total, len([g for g in groups.values() if g]), args.out))


if __name__ == "__main__":
    main()
