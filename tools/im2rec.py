#!/usr/bin/env python
"""im2rec — pack an image list into RecordIO (reference ``tools/im2rec.py``
/ ``tools/im2rec.cc``: parallel image → RecordIO packer).

Two subcommands, like the reference:

* ``--list``: walk an image directory and write ``prefix.lst``
  (``index\\tlabel\\trelpath`` per line, labels from per-directory class
  indices, with ``--train-ratio``/``--test-ratio`` splits).
* default: read ``prefix.lst`` and pack ``prefix.rec`` + ``prefix.idx``
  via ``MXIndexedRecordIO``, re-encoding each image (``--resize`` short
  side, ``--quality``, ``--color``) with a worker pool.

Usage::

    python tools/im2rec.py --list prefix image_root
    python tools/im2rec.py prefix image_root [--resize 256] [--quality 95]
"""
from __future__ import annotations

import argparse
import os
import random
import sys
from concurrent.futures import ThreadPoolExecutor

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


_EXTS = (".jpg", ".jpeg", ".png")


def list_images(root):
    cat = {}
    items = []
    for path, _, files in sorted(os.walk(root, followlinks=True)):
        for fname in sorted(files):
            if os.path.splitext(fname)[1].lower() not in _EXTS:
                continue
            rel = os.path.relpath(os.path.join(path, fname), root)
            label_dir = os.path.dirname(rel)
            if label_dir not in cat:
                cat[label_dir] = len(cat)
            items.append((len(items), cat[label_dir], rel))
    return items


def write_list(prefix, items, args):
    if args.shuffle:
        random.seed(100)
        random.shuffle(items)
    n_train = int(len(items) * args.train_ratio)
    chunks = {"": items}
    if args.train_ratio < 1.0:
        chunks = {"_train": items[:n_train], "_val": items[n_train:]}
    for suffix, chunk in chunks.items():
        with open(prefix + suffix + ".lst", "w") as f:
            for i, (idx, label, rel) in enumerate(chunk):
                f.write("%d\t%f\t%s\n" % (i, label, rel))


def read_list(path):
    with open(path) as f:
        for line in f:
            parts = line.strip().split("\t")
            if len(parts) < 3:
                continue
            yield int(parts[0]), [float(x) for x in parts[1:-1]], parts[-1]


def pack(prefix, root, args):
    if getattr(args, "pass_through", False):
        # native parallel packer (reference tools/im2rec.cc role):
        # already-encoded files are framed straight into .rec/.idx with
        # no decode/re-encode and no Python in the loop (the C++ side
        # parses the .lst itself — nothing to pre-read here)
        if args.resize or args.quality != 95 or args.color != 1 or \
                args.encoding != ".jpg":
            raise SystemExit(
                "--pass-through packs files untouched; it cannot honor "
                "--resize/--quality/--color/--encoding — drop those "
                "flags or use the re-encoding path")
        from mxnet_tpu._native import pack_recordio

        n = pack_recordio(prefix + ".lst", root, prefix + ".rec",
                          prefix + ".idx", nthreads=args.num_thread)
        if n is not None:
            print("wrote %s.rec (%d records, native pass-through)"
                  % (prefix, n))
            return
        print("native packer unavailable; using the Python path")

    from mxnet_tpu import recordio
    from mxnet_tpu.image import imread, resize_short

    import numpy as np

    lst = list(read_list(prefix + ".lst"))
    rec = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")

    def encode(item):
        idx, label, rel = item
        img = imread(os.path.join(root, rel), flag=args.color)
        if args.resize:
            img = resize_short(img, args.resize)
        label = label[0] if len(label) == 1 else np.asarray(label)
        header = recordio.IRHeader(0, label, idx, 0)
        return idx, recordio.pack_img(header, img, quality=args.quality,
                                      img_fmt=args.encoding)

    with ThreadPoolExecutor(max_workers=args.num_thread) as pool:
        for count, (idx, payload) in enumerate(pool.map(encode, lst)):
            rec.write_idx(idx, payload)
            if count % 1000 == 0 and count:
                print("packed %d images" % count)
    rec.close()
    print("wrote %s.rec (%d records)" % (prefix, len(lst)))


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("prefix")
    ap.add_argument("root")
    ap.add_argument("--list", action="store_true",
                    help="make an image list instead of a rec file")
    ap.add_argument("--shuffle", type=int, default=1)
    ap.add_argument("--train-ratio", type=float, default=1.0)
    ap.add_argument("--resize", type=int, default=0)
    ap.add_argument("--quality", type=int, default=95)
    ap.add_argument("--color", type=int, default=1)
    ap.add_argument("--encoding", default=".jpg")
    ap.add_argument("--num-thread", type=int, default=4)
    ap.add_argument("--pass-through", action="store_true",
                    help="pack already-encoded files natively (no "
                         "decode/re-encode; the C++ parallel packer)")
    args = ap.parse_args()
    if args.list:
        write_list(args.prefix, list_images(args.root), args)
    else:
        pack(args.prefix, args.root, args)


if __name__ == "__main__":
    main()
