#!/usr/bin/env python
"""Kill stray training jobs on this host (reference
``tools/kill-mxnet.py``): finds python processes whose command line
mentions the given script (default: any mxnet_tpu entry point) and
SIGTERMs them, SIGKILL after a grace period.

    python tools/kill_mxnet.py [script_name] [--force]
"""
import os
import signal
import sys
import time


def find_procs(needle):
    out = []
    for pid in os.listdir("/proc"):
        if not pid.isdigit() or int(pid) == os.getpid():
            continue
        try:
            with open("/proc/%s/cmdline" % pid, "rb") as f:
                cmd = f.read().replace(b"\0", b" ").decode(
                    "utf-8", "replace")
        except OSError:
            continue
        if "python" in cmd and needle in cmd:
            out.append((int(pid), cmd.strip()))
    return out


def main():
    args = [a for a in sys.argv[1:] if not a.startswith("--")]
    needle = args[0] if args else "mxnet_tpu"
    force = "--force" in sys.argv
    procs = find_procs(needle)
    if not procs:
        print("no matching processes")
        return
    for pid, cmd in procs:
        print("killing %d: %s" % (pid, cmd[:100]))
        try:
            os.kill(pid, signal.SIGTERM)
        except OSError:
            pass
    if force:
        time.sleep(2)
        for pid, _ in procs:
            try:
                os.kill(pid, signal.SIGKILL)
            except OSError:
                pass


if __name__ == "__main__":
    main()
