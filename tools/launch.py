#!/usr/bin/env python
"""launch.py — multi-process/multi-host job launcher.

Reference: ``tools/launch.py`` over dmlc-tracker (ssh/mpi/sge/yarn/local
launchers spawning scheduler+server+worker processes with ``DMLC_*``
env).  The TPU build has no parameter servers: every process is a
worker, rendezvous runs through ``jax.distributed`` (the TPU runtime's
coordination service), so the launcher only needs to spawn N copies of
the training script with the coordinator address and process ids.

    # local: N worker processes on this machine (CPU devices, tests)
    python tools/launch.py -n 4 --launcher local python train.py ...

    # ssh: one worker per host listed in a hostfile
    python tools/launch.py -n 2 --launcher ssh -H hosts python train.py

Workers read MXNET_COORDINATOR / MXNET_NUM_WORKERS / MXNET_WORKER_ID and
call ``mxnet_tpu.parallel.init_distributed()`` (or pass them straight to
``jax.distributed.initialize``).  On real TPU pods the runtime provides
these automatically and this launcher is unnecessary — it exists for the
reference's local/ssh cluster workflow.
"""
from __future__ import annotations

import argparse
import os
import socket
import subprocess
import sys


def _free_port():
    s = socket.socket()
    s.bind(("0.0.0.0", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def launch_local(num_workers, command, env):
    coordinator = "127.0.0.1:%d" % _free_port()
    procs = []
    for rank in range(num_workers):
        wenv = dict(env, MXNET_COORDINATOR=coordinator,
                    MXNET_NUM_WORKERS=str(num_workers),
                    MXNET_WORKER_ID=str(rank))
        procs.append(subprocess.Popen(command, env=wenv))
    rc = 0
    for p in procs:
        rc = p.wait() or rc
    return rc


def launch_ssh(num_workers, hostfile, command, env):
    import shlex

    with open(hostfile) as f:
        hosts = [h.strip() for h in f if h.strip()]
    if len(hosts) < num_workers:
        raise SystemExit("hostfile has %d hosts, need %d"
                         % (len(hosts), num_workers))
    coordinator = "%s:%d" % (hosts[0], 29400)
    passthrough = " ".join(
        shlex.quote("%s=%s" % (k, v)) for k, v in env.items()
        if k.startswith(("MXNET_", "MXTPU_", "JAX_", "XLA_")))
    cmd = " ".join(shlex.quote(c) for c in command)
    procs = []
    for rank in range(num_workers):
        remote = ("cd %s && env %s MXNET_COORDINATOR=%s "
                  "MXNET_NUM_WORKERS=%d MXNET_WORKER_ID=%d %s"
                  % (shlex.quote(os.getcwd()), passthrough, coordinator,
                     num_workers, rank, cmd))
        procs.append(subprocess.Popen(["ssh", hosts[rank], remote]))
    rc = 0
    for p in procs:
        rc = p.wait() or rc
    return rc


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("-n", "--num-workers", type=int, required=True)
    ap.add_argument("--launcher", choices=("local", "ssh"),
                    default="local")
    ap.add_argument("-s", "--num-servers", type=int, default=0,
                    help="accepted for reference-CLI parity; dist_tpu_sync"
                         " has no parameter servers (ignored with a"
                         " warning)")
    ap.add_argument("-H", "--hostfile", default=None)
    ap.add_argument("command", nargs=argparse.REMAINDER)
    args = ap.parse_args()
    if getattr(args, "num_servers", 0):
        print("WARNING: -s/--num-servers ignored: dist_tpu_sync is SPMD "
              "(no parameter servers); launching workers only",
              file=sys.stderr)
    if not args.command:
        raise SystemExit("no command given")
    env = dict(os.environ)
    if args.launcher == "local":
        sys.exit(launch_local(args.num_workers, args.command, env))
    if args.hostfile is None:
        raise SystemExit("--launcher ssh needs -H hostfile")
    sys.exit(launch_ssh(args.num_workers, args.hostfile, args.command,
                        env))


if __name__ == "__main__":
    main()
