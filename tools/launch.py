#!/usr/bin/env python
"""launch.py — multi-process/multi-host job launcher.

Reference: ``tools/launch.py`` over dmlc-tracker (ssh/mpi/sge/yarn/local
launchers spawning scheduler+server+worker processes with ``DMLC_*``
env).  The TPU build has no parameter servers: every process is a
worker, rendezvous runs through ``jax.distributed`` (the TPU runtime's
coordination service), so the launcher spawns N copies of the training
script with the coordinator address and process ids — and, like
dmlc-tracker, PROPAGATES FAILURE: the first worker that dies non-zero
tears the rest of the job down instead of leaving it hung on a
collective.

    # local: N worker processes on this machine (CPU devices, tests)
    python tools/launch.py -n 4 --launcher local python train.py ...

    # ssh: one worker per host listed in a hostfile
    python tools/launch.py -n 2 --launcher ssh -H hosts python train.py

    # tpu-vm: one worker per TPU-VM host (hostfile or
    # TPU_WORKER_HOSTNAMES metadata), jax.distributed env injected
    python tools/launch.py -n 4 --launcher tpu-vm -H hosts python train.py

    # gke: emit a kubectl-ready Indexed Job manifest (no cluster calls)
    python tools/launch.py -n 16 --launcher gke --gke-image IMG \
        --gke-output job.yaml python train.py ...

    # live elasticity: tell a RUNNING job (launched with --elastic-dir)
    # to re-form at a new size/plan without restarting — workers polling
    # the manifest migrate in memory (mxnet_tpu.parallel.elastic)
    python tools/launch.py -n 2 --scale-event --elastic-dir /shared/el \
        --plan data=2

Workers read MXNET_COORDINATOR / MXNET_NUM_WORKERS / MXNET_WORKER_ID and
call ``mxnet_tpu.parallel.init_distributed()`` (or pass them straight to
``jax.distributed.initialize``).  On real TPU pods the runtime provides
these automatically; the tpu-vm/gke modes exist for bring-up on plain
TPU-VM fleets and GKE clusters where nothing injects them for you.
"""
from __future__ import annotations

import argparse
import os
import shlex
import socket
import subprocess
import sys
import time


def _free_port():
    s = socket.socket()
    s.bind(("0.0.0.0", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _wait_propagating(procs, poll_s=0.2):
    """dmlc-tracker semantics: wait for all workers; the FIRST non-zero
    exit kills the remaining workers (a dead rank would otherwise hang
    every peer at its next collective) and becomes the job's rc."""
    rc = 0
    live = list(procs)
    try:
        while live:
            for p in list(live):
                ret = p.poll()
                if ret is None:
                    continue
                live.remove(p)
                if ret != 0 and rc == 0:
                    rc = ret
                    print("launch.py: worker pid %d exited %d; tearing "
                          "down %d remaining worker(s)"
                          % (p.pid, ret, len(live)), file=sys.stderr)
                    for q in live:
                        q.terminate()
            if live:
                time.sleep(poll_s)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return rc


def _worker_env(env, coordinator, num_workers, rank, elastic_dir=None):
    out = dict(env,
               MXNET_COORDINATOR=coordinator,
               MXNET_NUM_WORKERS=str(num_workers),
               MXNET_WORKER_ID=str(rank))
    if elastic_dir:
        out["MXNET_ELASTIC_DIR"] = elastic_dir
    return out


def launch_local(num_workers, command, env, elastic_dir=None):
    coordinator = "127.0.0.1:%d" % _free_port()
    procs = [subprocess.Popen(
        command, env=_worker_env(env, coordinator, num_workers, rank,
                                 elastic_dir=elastic_dir))
        for rank in range(num_workers)]
    return _wait_propagating(procs)


def emit_scale_event(directory, num_workers, plan=None, reason=""):
    """Publish a live-elasticity scale event for running workers to poll
    (``mxnet_tpu.parallel.elastic``): atomic rename of
    ``<dir>/scale_event.json`` with a monotonically increasing ``seq``.
    Deliberately stdlib-only and schema-identical to
    ``elastic.write_scale_event`` — the JSON file IS the contract, the
    same way the gke manifest is."""
    import json

    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, "scale_event.json")
    seq = 0
    try:
        with open(path) as f:
            seq = int(json.load(f).get("seq", 0))
    except (OSError, ValueError):
        pass
    payload = {"seq": seq + 1, "num_workers": int(num_workers),
               "plan": plan or None, "reason": reason}
    tmp = path + ".tmp.%d" % os.getpid()
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, path)
    print("launch.py: published scale event seq %d (%d workers%s) to %s"
          % (payload["seq"], num_workers,
             ", plan %s" % plan if plan else "", path))
    return 0


def _read_hosts(hostfile, num_workers):
    if hostfile:
        with open(hostfile) as f:
            hosts = [h.strip() for h in f if h.strip()
                     and not h.startswith("#")]
    elif os.environ.get("TPU_WORKER_HOSTNAMES"):
        # the TPU-VM metadata contract: comma-separated worker hosts
        hosts = os.environ["TPU_WORKER_HOSTNAMES"].split(",")
    else:
        raise SystemExit("need -H hostfile (or TPU_WORKER_HOSTNAMES for "
                         "tpu-vm)")
    if len(hosts) < num_workers:
        raise SystemExit("hostfile has %d hosts, need %d"
                         % (len(hosts), num_workers))
    return hosts


def launch_ssh(num_workers, hostfile, command, env, extra_env=()):
    hosts = _read_hosts(hostfile, num_workers)
    coordinator = "%s:%d" % (hosts[0], 29400)
    passthrough = " ".join(
        shlex.quote("%s=%s" % (k, v)) for k, v in env.items()
        if k.startswith(("MXNET_", "MXTPU_", "JAX_", "XLA_")))
    cmd = " ".join(shlex.quote(c) for c in command)
    procs = []
    for rank in range(num_workers):
        inject = ("MXNET_COORDINATOR=%s MXNET_NUM_WORKERS=%d "
                  "MXNET_WORKER_ID=%d" % (coordinator, num_workers, rank))
        inject += "".join(" %s" % shlex.quote(e) for e in extra_env)
        remote = ("cd %s && env %s %s %s"
                  % (shlex.quote(os.getcwd()), passthrough, inject, cmd))
        procs.append(subprocess.Popen(["ssh", hosts[rank], remote]))
    return _wait_propagating(procs)


def launch_tpu_vm(num_workers, hostfile, command, env):
    """One worker per TPU-VM host: ssh fan-out with the jax.distributed
    bring-up env injected directly (JAX_COORDINATOR_ADDRESS and friends
    are read by ``jax.distributed.initialize()`` with no arguments, so
    unmodified JAX scripts synchronize too, not only mxnet_tpu ones)."""
    hosts = _read_hosts(hostfile, num_workers)
    coordinator = "%s:%d" % (hosts[0], 8476)
    extra = ["JAX_COORDINATOR_ADDRESS=%s" % coordinator,
             "JAX_NUM_PROCESSES=%d" % num_workers]
    # per-rank JAX_PROCESS_ID rides through the generic injection below
    procs = []
    passthrough = " ".join(
        shlex.quote("%s=%s" % (k, v)) for k, v in env.items()
        if k.startswith(("MXNET_", "MXTPU_", "JAX_", "XLA_", "TPU_")))
    cmd = " ".join(shlex.quote(c) for c in command)
    for rank in range(num_workers):
        inject = ("MXNET_COORDINATOR=%s MXNET_NUM_WORKERS=%d "
                  "MXNET_WORKER_ID=%d JAX_PROCESS_ID=%d"
                  % (coordinator, num_workers, rank, rank))
        inject += "".join(" %s" % shlex.quote(e) for e in extra)
        remote = ("cd %s && env %s %s %s"
                  % (shlex.quote(os.getcwd()), passthrough, inject, cmd))
        procs.append(subprocess.Popen(["ssh", hosts[rank], remote]))
    return _wait_propagating(procs)


_GKE_TEMPLATE = """\
# generated by tools/launch.py --launcher gke — kubectl apply -f this.
# Indexed Job: N completions, one worker pod per index; the headless
# Service makes pod 0 resolvable as the jax.distributed coordinator.
apiVersion: v1
kind: Service
metadata:
  name: {name}-coord
spec:
  clusterIP: None
  selector:
    job-name: {name}
  ports:
  - port: {port}
---
apiVersion: batch/v1
kind: Job
metadata:
  name: {name}
spec:
  completions: {n}
  parallelism: {n}
  completionMode: Indexed
  backoffLimit: 0
  template:
    metadata:
      labels:
        job-name: {name}
    spec:
      subdomain: {name}-coord
      restartPolicy: Never
      containers:
      - name: worker
        image: {image}
        command: {command_json}
        env:
        - name: MXNET_WORKER_ID
          valueFrom:
            fieldRef:
              fieldPath: metadata.annotations['batch.kubernetes.io/job-completion-index']
        - name: JAX_PROCESS_ID
          valueFrom:
            fieldRef:
              fieldPath: metadata.annotations['batch.kubernetes.io/job-completion-index']
        - name: MXNET_NUM_WORKERS
          value: "{n}"
        - name: MXNET_COORDINATOR
          value: "{name}-0.{name}-coord:{port}"
        - name: JAX_COORDINATOR_ADDRESS
          value: "{name}-0.{name}-coord:{port}"
        - name: JAX_NUM_PROCESSES
          value: "{n}"
        resources:
          limits:
            google.com/tpu: {tpu_per_pod}
"""


def emit_gke(num_workers, command, image, name="mxtpu-train", port=8476,
             tpu_per_pod=4, output=None):
    """Emit a kubectl-ready Indexed Job manifest (the dmlc-tracker yarn
    role, GKE-shaped).  No cluster API calls: the manifest IS the
    deliverable, applied with kubectl by the operator."""
    import json as _json

    manifest = _GKE_TEMPLATE.format(
        name=name, n=num_workers, image=image, port=port,
        tpu_per_pod=tpu_per_pod, command_json=_json.dumps(command))
    if output:
        with open(output, "w") as f:
            f.write(manifest)
        print("wrote %s (kubectl apply -f %s)" % (output, output))
    else:
        print(manifest)
    return 0


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("-n", "--num-workers", type=int, required=True)
    ap.add_argument("--launcher",
                    choices=("local", "ssh", "tpu-vm", "gke"),
                    default="local")
    ap.add_argument("-s", "--num-servers", type=int, default=0,
                    help="accepted for reference-CLI parity; dist_tpu_sync"
                         " has no parameter servers (ignored with a"
                         " warning)")
    ap.add_argument("-H", "--hostfile", default=None)
    ap.add_argument("--gke-image", default=None,
                    help="container image for --launcher gke")
    ap.add_argument("--gke-name", default="mxtpu-train")
    ap.add_argument("--gke-tpu-per-pod", type=int, default=4)
    ap.add_argument("--gke-output", default=None,
                    help="write the Job manifest here (default: stdout)")
    ap.add_argument("--elastic-dir", default=None,
                    help="shared directory for live-elasticity scale "
                         "events; exported to workers as "
                         "MXNET_ELASTIC_DIR (see docs/fault_tolerance.md "
                         "'Live elasticity')")
    ap.add_argument("--scale-event", action="store_true",
                    help="instead of launching, publish a scale event to "
                         "--elastic-dir telling a RUNNING elastic job to "
                         "re-form at -n workers (optionally --plan)")
    ap.add_argument("--plan", default=None,
                    help="new parallel plan spec for --scale-event, e.g. "
                         "'data=2,model=2'")
    ap.add_argument("--scale-reason", default="launch.py --scale-event",
                    help="reason string recorded in the scale event")
    ap.add_argument("command", nargs=argparse.REMAINDER)
    args = ap.parse_args()
    if args.scale_event:
        if not args.elastic_dir:
            raise SystemExit("--scale-event needs --elastic-dir")
        sys.exit(emit_scale_event(args.elastic_dir, args.num_workers,
                                  plan=args.plan,
                                  reason=args.scale_reason))
    if getattr(args, "num_servers", 0):
        print("WARNING: -s/--num-servers ignored: dist_tpu_sync is SPMD "
              "(no parameter servers); launching workers only",
              file=sys.stderr)
    if not args.command:
        raise SystemExit("no command given")
    env = dict(os.environ)
    if args.elastic_dir:
        # ssh/tpu-vm inject via the MXNET_* passthrough; local via
        # _worker_env
        env["MXNET_ELASTIC_DIR"] = args.elastic_dir
    if args.launcher == "local":
        sys.exit(launch_local(args.num_workers, args.command, env,
                              elastic_dir=args.elastic_dir))
    if args.launcher == "gke":
        if not args.gke_image:
            raise SystemExit("--launcher gke needs --gke-image")
        sys.exit(emit_gke(args.num_workers, args.command, args.gke_image,
                          name=args.gke_name,
                          tpu_per_pod=args.gke_tpu_per_pod,
                          output=args.gke_output))
    if args.launcher == "tpu-vm":
        sys.exit(launch_tpu_vm(args.num_workers, args.hostfile,
                               args.command, env))
    if args.hostfile is None:
        raise SystemExit("--launcher ssh needs -H hostfile")
    sys.exit(launch_ssh(args.num_workers, args.hostfile, args.command,
                        env))


if __name__ == "__main__":
    main()
