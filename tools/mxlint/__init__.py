"""mxlint — project-invariant static analysis for tpu-mx.

Ten PRs of conventions (the fold_in RNG discipline, the compile-once
contract, bounded collectives, join-with-timeout teardown, the
``MXNET_*`` env catalog) live in reviewers' memories; mxlint turns them
into machine-checked invariants.  See ``docs/static_analysis.md``.

Usage::

    python -m tools.mxlint [paths] [--select MX001,..] [--ignore ..]
                           [--baseline FILE] [--write-baseline]
                           [--prune-baseline] [--json]

Checkers (each documented in docs/static_analysis.md):

========  ==============================================================
MX001     host sync (float()/.item()/np.asarray/device_get) on a traced
          value inside a jit/shard_map/scan-visible function
MX002     collective (psum/all_gather/psum_scatter/...) under
          value-dependent Python control flow — the multi-host deadlock
MX003     raw np.random.*/random.* / time-seeded RNG outside the
          sanctioned fold_in sites
MX004     every MXNET_* env read documented in docs/env_vars.md and
          vice-versa
MX005     every faults.inject(site) name registered in
          testing/faults.py SITES and exercised by a test
MX006     a class that starts a Thread/Process must tear it down via a
          close()/_halt()-style method that joins with a timeout
MX007     buffer reused after being passed to a donate_argnums
          executable
MX008     bare except / except Exception that can swallow MXNetError
          without re-raising
========  ==============================================================
"""
from .engine import (  # noqa: F401
    Finding, Checker, ProjectChecker, register, all_checkers,
    run_paths, load_baseline, write_baseline, DEFAULT_BASELINE,
)
