"""CLI: ``python -m tools.mxlint [paths] [options]``.

Exit codes (bench_util-style — machine-parseable, never a traceback for
a finding): 0 = clean (baselined debt allowed), 1 = at least one
non-baselined finding or a parse error, 2 = stale baseline under
``--prune-baseline``, 3 = usage error.
"""
import argparse
import os
import sys

from . import engine


def _codes(text):
    return {c.strip().upper() for c in text.split(",") if c.strip()}


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m tools.mxlint",
        description="Project-invariant static analysis for tpu-mx "
                    "(docs/static_analysis.md).")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to scan (default: mxnet_tpu tools "
                         "bench*.py __graft_entry__.py under the repo "
                         "root)")
    ap.add_argument("--select", default="",
                    help="comma-separated codes to run (default: all)")
    ap.add_argument("--ignore", default="",
                    help="comma-separated codes to skip")
    ap.add_argument("--baseline", default=None, metavar="FILE",
                    help="baseline file (default: tools/mxlint/"
                         "baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report grandfathered findings too")
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite the baseline from the current "
                         "findings and exit 0")
    ap.add_argument("--prune-baseline", action="store_true",
                    help="fail (exit 2) when a baseline entry no longer "
                         "matches any finding — grandfathered debt may "
                         "only shrink")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit one JSON object (stable schema) instead "
                         "of text")
    ap.add_argument("--root", default=None,
                    help="repo root (default: auto-detected)")
    ap.add_argument("--list-checkers", action="store_true",
                    help="print the checker catalog and exit")
    args = ap.parse_args(argv)

    checkers = engine.all_checkers()
    if args.list_checkers:
        for code in sorted(checkers):
            cls = checkers[code]
            print("%s  %-24s %s" % (code, cls.name,
                                    (cls.__doc__ or "").strip()
                                    .split("\n")[0]))
        return 0

    select = _codes(args.select)
    ignore = _codes(args.ignore)
    unknown = (select | ignore) - set(checkers) - {"MX000"}
    if unknown:
        print("mxlint: unknown code(s): %s (known: %s)"
              % (",".join(sorted(unknown)), ",".join(sorted(checkers))),
              file=sys.stderr)
        return 3

    root = os.path.abspath(args.root or engine.find_root(
        args.paths[0] if args.paths else os.getcwd()))
    paths = args.paths
    if not paths:
        paths = [os.path.join(root, "mxnet_tpu"),
                 os.path.join(root, "tools"),
                 os.path.join(root, "__graft_entry__.py")]
        import glob as _glob
        paths += sorted(_glob.glob(os.path.join(root, "bench*.py")))
        paths = [p for p in paths if os.path.exists(p)]
    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        print("mxlint: no such path: %s" % ", ".join(missing),
              file=sys.stderr)
        return 3

    findings, parse_errors = engine.run_paths(
        paths, root=root, select=select or None, ignore=ignore or None)

    baseline_path = args.baseline or engine.DEFAULT_BASELINE
    if args.write_baseline:
        payload = engine.write_baseline(baseline_path, findings)
        print("mxlint: wrote %d baseline entries (%d findings) to %s"
              % (len(payload["entries"]), len(findings),
                 os.path.relpath(baseline_path, root)))
        return 0

    baseline = {} if args.no_baseline \
        else engine.load_baseline(baseline_path)
    stale = engine.apply_baseline(findings, baseline)
    # a subset scan can't tell whether debt outside its paths was paid
    # — only report stale entries the scan actually covered
    scanned = [os.path.relpath(os.path.abspath(p), root)
               .replace(os.sep, "/") for p in paths]
    stale = {k: v for k, v in stale.items()
             if any(s in (".", k.split("::", 1)[0]) or
                    k.startswith(s + "/") for s in scanned)}

    if args.as_json:
        engine.emit_json(findings, parse_errors, stale)
    else:
        shown = [f for f in findings if not f.baselined] + parse_errors
        for f in shown:
            print(f.render())
        n_base = sum(1 for f in findings if f.baselined)
        tail = "mxlint: %d finding(s)" % len(shown)
        if n_base:
            tail += ", %d baselined" % n_base
        if stale:
            tail += ", %d STALE baseline entr%s" % (
                len(stale), "y" if len(stale) == 1 else "ies")
        print(tail)
        for key in sorted(stale):
            print("  stale baseline: %s (debt paid — remove the entry "
                  "or run --write-baseline)" % key)

    if args.prune_baseline and stale:
        return 2
    if any(not f.baselined for f in findings) or parse_errors:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
