"""Shared AST machinery for the mxlint checkers.

Everything here is *approximate on purpose*: mxlint is a linter, not a
verifier.  Name resolution follows import aliases within one module,
"traced" functions are found by local evidence (decorator, or the name
being handed to jit/shard_map/scan/...), and value taint is a single
forward pass over parameter-derived names.  Findings the heuristics get
wrong are suppressed inline (``# mxlint: disable=CODE``) — precision
beats recall for a gate that runs in tier-1.
"""
import ast

# ---------------------------------------------------------------------------
# import-alias resolution


def import_aliases(tree):
    """Map local name -> canonical dotted prefix for a module.

    ``import jax.numpy as jnp`` -> {'jnp': 'jax.numpy'};
    ``from jax import lax`` -> {'lax': 'jax.lax'};
    ``from .testing import faults`` -> {'faults': 'testing.faults'}
    (relative dots are dropped — suffix matching absorbs them).
    """
    aliases = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                aliases[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            for a in node.names:
                if a.name == "*":
                    continue
                full = ("%s.%s" % (mod, a.name)) if mod else a.name
                aliases[a.asname or a.name] = full
    return aliases


def dotted(node, aliases):
    """Canonical dotted name of a Name/Attribute chain, or None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    root = aliases.get(node.id, node.id)
    parts.append(root)
    return ".".join(reversed(parts))


def call_name(call, aliases):
    """Canonical dotted name of a call's callee, or None."""
    return dotted(call.func, aliases)


def matches(name, suffixes):
    """True when canonical ``name`` ends with any of ``suffixes``
    (component-aligned: 'jax.jit' matches 'jit' and 'jax.jit', not
    'myjit')."""
    if name is None:
        return False
    for suf in suffixes:
        if name == suf or name.endswith("." + suf):
            return True
    return False


# ---------------------------------------------------------------------------
# parent links / enclosing scopes


def parent_map(tree):
    parents = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


def enclosing(node, parents, kinds):
    """Nearest ancestor of one of ``kinds`` (a tuple of AST classes)."""
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, kinds):
            return cur
        cur = parents.get(cur)
    return None


_FUNCS = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def qualname(node, parents):
    """Dotted human name of the def/class chain enclosing ``node``."""
    names = []
    cur = node
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.ClassDef)):
            names.append(cur.name)
        elif isinstance(cur, ast.Lambda):
            names.append("<lambda>")
        cur = parents.get(cur)
    return ".".join(reversed(names)) or "<module>"


# ---------------------------------------------------------------------------
# traced-function discovery (MX001/MX002)

# callables whose function argument is traced by jax
TRACING_CALLS = (
    "jax.jit", "jit", "pjit", "jax.pmap", "pmap",
    "shard_map", "jax.experimental.shard_map.shard_map",
    "jax.checkpoint", "jax.remat", "remat", "checkpoint",
    "lax.scan", "scan", "lax.cond", "cond", "lax.while_loop",
    "while_loop", "lax.fori_loop", "fori_loop", "lax.switch",
    "lax.map", "lax.associative_scan",
    "jax.vmap", "vmap", "jax.grad", "grad", "jax.value_and_grad",
    "value_and_grad", "jax.custom_vjp", "custom_vjp", "jax.custom_jvp",
    "custom_jvp", "jax.linearize", "jax.vjp", "jax.jvp",
    "jax.eval_shape", "eval_shape",
)

def _decorator_traces(dec, aliases):
    """True when a decorator node is a tracing transform (possibly via
    functools.partial(jax.jit, ...))."""
    if isinstance(dec, ast.Call):
        name = call_name(dec, aliases)
        if matches(name, TRACING_CALLS):
            return True
        if matches(name, ("functools.partial", "partial")) and dec.args:
            return matches(dotted(dec.args[0], aliases), TRACING_CALLS)
        return False
    return matches(dotted(dec, aliases), TRACING_CALLS)


def traced_functions(tree, aliases, parents):
    """The set of FunctionDef/Lambda nodes whose bodies run under a jax
    trace, by local evidence:

    * decorated with jit/checkpoint/custom_vjp/... (or a
      functools.partial of one);
    * their name (bare or ``self.name``) appears as an argument to a
      tracing call anywhere in the module;
    * defined lexically inside a traced function (nested helpers run
      at trace time);
    * a lambda passed directly to a tracing call.
    """
    defs_by_name = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs_by_name.setdefault(node.name, []).append(node)

    traced = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(_decorator_traces(d, aliases)
                   for d in node.decorator_list):
                traced.add(node)
        elif isinstance(node, ast.Call):
            if not matches(call_name(node, aliases), TRACING_CALLS):
                continue
            for arg in list(node.args) + [k.value for k in node.keywords]:
                if isinstance(arg, ast.Lambda):
                    traced.add(arg)
                elif isinstance(arg, ast.Name):
                    traced.update(defs_by_name.get(arg.id, ()))
                elif isinstance(arg, ast.Attribute):
                    traced.update(defs_by_name.get(arg.attr, ()))

    # nested defs of traced functions are traced too (fixpoint over the
    # lexical tree — one sweep per nesting level)
    changed = True
    while changed:
        changed = False
        for node in ast.walk(tree):
            if not isinstance(node, _FUNCS) or node in traced:
                continue
            anc = enclosing(node, parents, _FUNCS)
            while anc is not None and anc not in traced:
                anc = enclosing(anc, parents, _FUNCS)
            if anc is not None:
                traced.add(node)
                changed = True
    return traced


# ---------------------------------------------------------------------------
# taint: parameter-derived values within one function

# attribute/call results that are static at trace time even on a traced
# array (shapes and dtypes are compile-time constants)
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "aval", "sharding"}
_STATIC_CALLS = ("len", "range", "enumerate", "isinstance", "type",
                 "getattr", "hasattr", "zip")


def _param_names(fn):
    a = fn.args
    names = [p.arg for p in
             list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return set(names)


def contains_taint(node, tainted, aliases):
    """True when ``node`` references a tainted name *as a value* —
    descending, but treating shape/dtype accesses and len()/range()
    results as static."""
    if isinstance(node, ast.Attribute) and node.attr in _STATIC_ATTRS:
        return False
    if isinstance(node, ast.Call):
        if matches(call_name(node, aliases), _STATIC_CALLS):
            return False
        kids = list(node.args) + [k.value for k in node.keywords]
        if isinstance(node.func, ast.Attribute):
            kids.append(node.func.value)  # method on a tainted receiver
        return any(contains_taint(k, tainted, aliases) for k in kids)
    if isinstance(node, ast.Name):
        return node.id in tainted
    return any(contains_taint(c, tainted, aliases)
               for c in ast.iter_child_nodes(node))


def tainted_names(fn, aliases):
    """Forward may-taint pass: parameters are tainted; an assignment
    whose RHS contains a tainted value taints its targets.  Two sweeps
    approximate loop back-edges."""
    tainted = _param_names(fn)
    body = fn.body if isinstance(fn.body, list) else [fn.body]
    for _ in range(2):
        before = len(tainted)
        for stmt in body:
            for node in ast.walk(stmt):
                if isinstance(node, _FUNCS):
                    continue
                value = None
                targets = []
                if isinstance(node, ast.Assign):
                    value, targets = node.value, node.targets
                elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                    value = node.value
                    targets = [node.target]
                elif isinstance(node, ast.For):
                    value, targets = node.iter, [node.target]
                if value is None:
                    continue
                if contains_taint(value, tainted, aliases):
                    for t in targets:
                        for leaf in ast.walk(t):
                            if isinstance(leaf, ast.Name):
                                tainted.add(leaf.id)
        if len(tainted) == before:
            break
    return tainted
