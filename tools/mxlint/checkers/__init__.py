"""Importing this package registers every mxlint checker."""
from . import tracing      # noqa: F401  MX001, MX002
from . import rng          # noqa: F401  MX003
from . import registries   # noqa: F401  MX004, MX005
from . import teardown     # noqa: F401  MX006
from . import donation     # noqa: F401  MX007
from . import excepts      # noqa: F401  MX008
