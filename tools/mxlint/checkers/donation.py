"""MX007 — donation reuse.

A buffer passed at a ``donate_argnums`` position of a jitted (or AOT
``.lower().compile()``d) executable is dead the moment the call
dispatches — XLA may alias its pages for the output.  Reading the
Python name afterwards returns deleted-array errors on TPU and silent
garbage in some donation modes.  The checker tracks names assigned
from ``jax.jit(..., donate_argnums=...)`` (and their ``self.attr``
form plus AOT derivatives) within a module, then flags loads of a
donated argument after the consuming call without an intervening
rebind.
"""
import ast

from .. import astutil
from ..engine import Checker, register

_JITS = ("jax.jit", "jit", "pjit")
_FUNCS = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _donated_positions(call, aliases):
    """The literal donate_argnums positions of a jit call, or None."""
    if not astutil.matches(astutil.call_name(call, aliases), _JITS):
        return None
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return (v.value,)
        if isinstance(v, (ast.Tuple, ast.List)):
            out = []
            for e in v.elts:
                if isinstance(e, ast.Constant) and \
                        isinstance(e.value, int):
                    out.append(e.value)
                else:
                    return None
            return tuple(out)
        return None
    return None


def _unwrap_aot(node):
    """``X.lower(...).compile(...)`` -> X, else the node itself."""
    cur = node
    for attr in ("compile", "lower"):
        if isinstance(cur, ast.Call) and \
                isinstance(cur.func, ast.Attribute) and \
                cur.func.attr == attr:
            cur = cur.func.value
        else:
            return node
    return cur


@register
class DonationReuse(Checker):
    """Use of a buffer after it was passed at a donate_argnums position
    — the executable may already have aliased its memory."""

    code = "MX007"
    name = "donation-reuse"
    hint = ("rebind the name to the executable's output (the donation "
            "idiom is x = f(x)), copy before the call, or drop "
            "donate_argnums for that argument")

    def check(self, ctx):
        donors = self._collect_donors(ctx)
        if not donors:
            return []
        findings = []
        for fn in ast.walk(ctx.tree):
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                findings.extend(self._check_fn(fn, donors, ctx))
        return findings

    def _collect_donors(self, ctx):
        """name/attr -> donated positions, for assignments of donating
        jits (including AOT ``.lower().compile()`` chains over an
        already-known donor)."""
        donors = {}
        for _ in range(2):  # second pass resolves AOT-of-donor chains
            for node in ast.walk(ctx.tree):
                if not isinstance(node, ast.Assign) or \
                        len(node.targets) != 1:
                    continue
                value = _unwrap_aot(node.value)
                pos = None
                if isinstance(value, ast.Call):
                    pos = _donated_positions(value, ctx.aliases)
                if pos is None and value is not node.value:
                    # AOT chain over a name that is itself a donor
                    key = self._target_key(value)
                    pos = donors.get(key)
                if pos is None:
                    continue
                key = self._target_key(node.targets[0])
                if key:
                    donors[key] = pos
        return donors

    def _target_key(self, node):
        if isinstance(node, ast.Name):
            return node.id
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and \
                node.value.id == "self":
            return "self." + node.attr
        return None

    def _check_fn(self, fn, donors, ctx):
        """Per-*statement* event processing — loads, then donations,
        then stores.  Within ``new = step(state, x)`` the argument load
        of ``state`` precedes the donation, and in the canonical rebind
        ``state = step(state, x)`` the store lands after it, so neither
        self-flags; only a load in a *later* statement does."""
        findings = []
        by_stmt = {}  # stmt -> {"load"/"donate"/"store": [(name, node)]}
        for node in ast.walk(fn):
            owner = astutil.enclosing(node, ctx.parents, _FUNCS)
            if owner is not fn:
                continue
            stmt = astutil.enclosing(node, ctx.parents, (ast.stmt,))
            if stmt is None:
                continue
            ev = by_stmt.setdefault(
                stmt, {"load": [], "donate": [], "store": []})
            if isinstance(node, ast.Call):
                key = self._target_key(node.func)
                pos = donors.get(key)
                if pos:
                    for i in pos:
                        if i < len(node.args) and \
                                isinstance(node.args[i], ast.Name):
                            ev["donate"].append(
                                (node.args[i].id, node))
            elif isinstance(node, ast.Name):
                kind = "load" if isinstance(node.ctx, ast.Load) \
                    else "store"
                ev[kind].append((node.id, node))
        dead = {}  # name -> donating call node
        for stmt in sorted(by_stmt,
                           key=lambda s: (s.lineno, s.col_offset)):
            ev = by_stmt[stmt]
            for name, node in ev["load"]:
                if name not in dead:
                    continue
                donor = dead.pop(name)  # report once per donation
                qn = astutil.qualname(fn, ctx.parents)
                findings.append(ctx.finding(
                    node, self.code,
                    "%r is read after being donated to the executable "
                    "called at line %d — the buffer may already be "
                    "aliased" % (name, donor.lineno),
                    hint=self.hint,
                    symbol="%s:%s" % (qn, name)))
            for name, node in ev["donate"]:
                dead[name] = node
            for name, node in ev["store"]:
                dead.pop(name, None)
        return findings
