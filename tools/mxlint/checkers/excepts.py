"""MX008 — bare except swallows MXNetError.

Every typed failure this codebase worked to surface — MXNetError and
its subclasses (TrainingPreempted, CorruptCheckpoint, RecompileStorm,
StepHung...) — dies silently inside a ``except:`` / ``except
Exception:`` handler that never re-raises.  Catch the broad type for a
*fallback*, but let the project's typed errors through first
(``except MXNetError: raise``) or re-raise on exit.
"""
import ast

from .. import astutil
from ..engine import Checker, register

_BROAD = ("Exception", "BaseException", "builtins.Exception",
          "builtins.BaseException")
# the project's typed-error family: an earlier handler naming one of
# these (or re-raising) is the sanctioned pattern
_TYPED = ("MXNetError", "TrainingPreempted", "TrainingDiverged",
          "StepHung", "RecompileStorm", "CorruptCheckpoint")


def _names_in_type(node, aliases):
    if node is None:
        return [None]
    if isinstance(node, ast.Tuple):
        return [astutil.dotted(e, aliases) for e in node.elts]
    return [astutil.dotted(node, aliases)]


@register
class BareExceptSwallows(Checker):
    """A bare ``except:`` / ``except Exception:`` with no re-raise and
    no preceding MXNetError handler — the typed errors PRs 2-9 raise
    (preemption, corrupt checkpoint, step hang...) vanish here."""

    code = "MX008"
    name = "bare-except-swallows-mxneterror"
    hint = ("insert `except MXNetError: raise` before the broad "
            "handler, re-raise inside it, or narrow the caught type; "
            "a deliberate best-effort fallback carries "
            "# mxlint: disable=MX008")

    def check(self, ctx):
        findings = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Try):
                continue
            typed_seen = False
            for handler in node.handlers:
                names = _names_in_type(handler.type, ctx.aliases)
                if any(n and astutil.matches(n, _TYPED)
                       for n in names):
                    typed_seen = True
                    continue
                broad = any(n is None or astutil.matches(n, _BROAD)
                            for n in names)
                if not broad or typed_seen:
                    continue
                if any(isinstance(s, ast.Raise)
                       for s in ast.walk(handler)):
                    continue
                qn = astutil.qualname(handler, ctx.parents)
                what = "bare except:" if handler.type is None else \
                    "except %s:" % "/".join(str(n) for n in names)
                findings.append(ctx.finding(
                    handler, self.code,
                    "%s in %s swallows MXNetError (and every typed "
                    "subclass) without re-raising" % (what, qn),
                    hint=self.hint, symbol=qn))
        return findings
