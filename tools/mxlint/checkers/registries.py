"""MX004 (env-var registry) and MX005 (fault-site registry).

Both are *project* checkers: they compare the whole canonical code set
(mxnet_tpu/, tools/, bench*.py, __graft_entry__.py) against a
committed registry file, regardless of which paths the CLI was pointed
at — a subset scan must not report half a registry as drift.

MX004: every ``MXNET_*`` name the code actually accesses (``get_env``,
``os.environ``/``os.getenv`` in any form; ``MXTPU_`` aliases
canonicalize to ``MXNET_``) must have a row in ``docs/env_vars.md``,
and every documented row must still be accessed somewhere.

MX005: every literal ``faults.inject("site")`` must name an entry of
``mxnet_tpu/testing/faults.py::SITES``, SITES keys must be unique, and
every registered site must be exercised by at least one test under
``tests/``.
"""
import ast
import os
import re

from .. import astutil
from ..engine import Finding, ProjectChecker, register

# ---------------------------------------------------------------------------
# MX004

_ENV_DOC = "docs/env_vars.md"
_ENV_PREFIXES = ("MXNET_", "MXTPU_")
_GET_ENV = ("get_env", "base.get_env", "mxnet_tpu.base.get_env")
_OS_GET = ("os.environ.get", "environ.get", "os.getenv", "getenv",
           "os.environ.setdefault", "environ.setdefault",
           "os.environ.pop", "environ.pop")
_ENVIRON = ("os.environ", "environ")
# first-cell token of a markdown table row
_DOC_ROW_RE = re.compile(r"^\s*\|([^|]*)\|")
_VAR_RE = re.compile(r"MXNET_[A-Z0-9_]+[A-Z0-9]")


def _canon(name):
    """MXTPU_X and bare X canonicalize to MXNET_X (get_env parity)."""
    if name.startswith("MXTPU_"):
        return "MXNET_" + name[len("MXTPU_"):]
    if not name.startswith("MXNET_"):
        return "MXNET_" + name
    return name


def _literal_str(node):
    return node.value if isinstance(node, ast.Constant) and \
        isinstance(node.value, str) else None


def _str_consts(tree):
    """Name -> [string literals it may hold], from simple assignments
    (``ENV_VAR = "MXNET_FAULT_INJECT"``) and for-loops over literal
    tuples (``for key in ("MXTPU_X", "MXNET_X"):``) — the two ways
    this codebase names an env key indirectly."""
    consts = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            lit = _literal_str(node.value)
            if lit is not None:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        consts.setdefault(t.id, []).append(lit)
        elif isinstance(node, ast.For) and \
                isinstance(node.target, ast.Name) and \
                isinstance(node.iter, (ast.Tuple, ast.List)):
            lits = [_literal_str(e) for e in node.iter.elts]
            if lits and all(l is not None for l in lits):
                consts.setdefault(node.target.id, []).extend(lits)
    return consts


def _key_strings(node, consts):
    """Possible string values of an env-key expression."""
    lit = _literal_str(node)
    if lit is not None:
        return [lit]
    if isinstance(node, ast.Name):
        return consts.get(node.id, [])
    return []


def _env_reads(ctx):
    """[(canonical_name, node)] for every env access in one file."""
    out = []
    consts = _str_consts(ctx.tree)
    for node in ast.walk(ctx.tree):
        names, via_get_env = [], False
        if isinstance(node, ast.Call):
            callee = astutil.call_name(node, ctx.aliases)
            if astutil.matches(callee, _GET_ENV) and node.args:
                names = _key_strings(node.args[0], consts)
                via_get_env = True
            elif astutil.matches(callee, _OS_GET) and node.args:
                names = _key_strings(node.args[0], consts)
        elif isinstance(node, ast.Subscript):
            base = astutil.dotted(node.value, ctx.aliases)
            if astutil.matches(base, _ENVIRON):
                names = _key_strings(node.slice, consts)
        elif isinstance(node, ast.Compare):
            if len(node.ops) == 1 and \
                    isinstance(node.ops[0], (ast.In, ast.NotIn)):
                base = astutil.dotted(node.comparators[0], ctx.aliases)
                if astutil.matches(base, _ENVIRON):
                    names = _key_strings(node.left, consts)
        for name in names:
            if via_get_env:
                # get_env prepends the prefix itself; unprefixed
                # literals are env knobs too
                name = _canon(name)
            if name.startswith(_ENV_PREFIXES):
                out.append((_canon(name), node))
    return out


@register
class EnvRegistry(ProjectChecker):
    """Every MXNET_* env var the code reads must have a row in
    docs/env_vars.md, and every documented row must still be read —
    the catalog is the contract, drift makes it folklore."""

    code = "MX004"
    name = "env-var-registry"
    hint = ("add a `| `MXNET_X` | default | effect |` row to "
            "docs/env_vars.md (or delete the stale row / the dead "
            "read)")

    def check_project(self, project):
        findings = []
        read_at = {}  # canonical name -> first (relpath, node)
        for ctx in project.library_files():
            for name, node in _env_reads(ctx):
                read_at.setdefault(name, (ctx.relpath, node))

        doc = project.read(_ENV_DOC)
        if doc is None:
            return [Finding(_ENV_DOC, 1, 1, self.code,
                            "docs/env_vars.md not found — the env-var "
                            "catalog is gone", hint=self.hint,
                            symbol="missing-doc")]
        documented = {}  # canonical name -> first doc line
        for i, line in enumerate(doc.splitlines(), 1):
            m = _DOC_ROW_RE.match(line)
            if not m:
                continue
            for var in _VAR_RE.findall(m.group(1)):
                documented.setdefault(_canon(var), i)

        for name in sorted(set(read_at) - set(documented)):
            rel, node = read_at[name]
            findings.append(Finding(
                rel, node.lineno, node.col_offset + 1, self.code,
                "env var %s is read here but has no row in "
                "docs/env_vars.md" % name,
                hint=self.hint, symbol=name))
        for name in sorted(set(documented) - set(read_at)):
            findings.append(Finding(
                _ENV_DOC, documented[name], 1, self.code,
                "documented env var %s is never read under mxnet_tpu/"
                "tools/bench*.py — stale row (or the reader was "
                "removed without the doc)" % name,
                hint=self.hint, symbol=name))
        return findings


# ---------------------------------------------------------------------------
# MX005

_FAULTS_MOD = "mxnet_tpu/testing/faults.py"
_INJECT = ("faults.inject", "inject", "testing.faults.inject",
           "mxnet_tpu.testing.faults.inject")
_ACTIVE = ("faults.active", "active")


@register
class FaultSiteRegistry(ProjectChecker):
    """Every faults.inject(site) literal must be registered in
    testing/faults.py SITES, names must be unique, and each registered
    site needs at least one test exercising it — an unexercised fault
    hook is dead chaos coverage."""

    code = "MX005"
    name = "fault-site-registry"
    hint = ("register the site in mxnet_tpu/testing/faults.py SITES "
            "with a description, and arm it from a chaos test "
            "(MXNET_FAULT_INJECT=<site>:<action>)")

    def check_project(self, project):
        findings = []
        sites, dupes, sites_node = self._registry(project)
        if sites is None:
            return [Finding(_FAULTS_MOD, 1, 1, self.code,
                            "no SITES registry dict found in "
                            "testing/faults.py", hint=self.hint,
                            symbol="missing-registry")]
        for name, line in dupes:
            findings.append(Finding(
                _FAULTS_MOD, line, 1, self.code,
                "fault site %r registered twice in SITES" % name,
                hint="keep one entry per site", symbol="dup:" + name))

        used = {}  # site -> first (relpath, node)
        for ctx in project.library_files():
            if ctx.relpath == _FAULTS_MOD:
                continue
            for node in ast.walk(ctx.tree):
                if not isinstance(node, ast.Call) or not node.args:
                    continue
                callee = astutil.call_name(node, ctx.aliases)
                if not astutil.matches(callee, _INJECT) and \
                        not astutil.matches(callee, _ACTIVE):
                    continue
                lit = _literal_str(node.args[0])
                if lit is None:
                    continue  # dynamic site: judged at its callers
                used.setdefault(lit, (ctx.relpath, node))
                if lit not in sites:
                    findings.append(Finding(
                        ctx.relpath, node.lineno, node.col_offset + 1,
                        self.code,
                        "fault site %r is injected here but not "
                        "registered in testing/faults.py SITES" % lit,
                        hint=self.hint, symbol="unregistered:" + lit))

        test_blob = self._tests_text(project)
        for name in sorted(sites):
            if test_blob is not None and \
                    not re.search(r"\b%s\b" % re.escape(name),
                                  test_blob):
                findings.append(Finding(
                    _FAULTS_MOD, sites[name], 1, self.code,
                    "registered fault site %r is not referenced by any "
                    "test under tests/ — no chaos coverage" % name,
                    hint=self.hint, symbol="untested:" + name))
        return findings

    def _registry(self, project):
        src = project.read(_FAULTS_MOD)
        if src is None:
            return None, [], None
        try:
            tree = ast.parse(src)
        except SyntaxError:
            return None, [], None
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and \
                    any(isinstance(t, ast.Name) and t.id == "SITES"
                        for t in node.targets) and \
                    isinstance(node.value, ast.Dict):
                sites, dupes = {}, []
                for k in node.value.keys:
                    lit = _literal_str(k)
                    if lit is None:
                        continue
                    if lit in sites:
                        dupes.append((lit, k.lineno))
                    else:
                        sites[lit] = k.lineno
                return sites, dupes, node
        return None, [], None

    def _tests_text(self, project):
        tests_dir = os.path.join(project.root, "tests")
        if not os.path.isdir(tests_dir):
            return None
        chunks = []
        for dirpath, dirnames, filenames in os.walk(tests_dir):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    try:
                        with open(os.path.join(dirpath, fn), "r",
                                  encoding="utf-8",
                                  errors="replace") as f:
                            chunks.append(f.read())
                    except OSError:
                        pass
        return "\n".join(chunks)
