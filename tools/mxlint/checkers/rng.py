"""MX003 — RNG discipline (the PR 9 fold_in contract).

Library randomness must come from an explicitly-seeded generator (a
``np.random.RandomState(seed)`` / ``Generator(Philox(key=...))``
instance, ``mxnet_tpu.random.next_key()``) so runs replay bit-exactly
across worker counts and resumes.  Global-state draws
(``np.random.uniform``, ``random.random``, unseeded/time-seeded
constructors) silently couple results to call order and wall clock.
"""
import ast

from .. import astutil
from ..engine import Checker, register

# module-level stateful draws on the *global* numpy RNG
_NP_GLOBAL = tuple(
    "numpy.random." + f for f in (
        "seed", "random", "rand", "randn", "randint", "random_sample",
        "ranf", "sample", "uniform", "normal", "standard_normal",
        "permutation", "shuffle", "choice", "beta", "binomial",
        "multinomial", "poisson", "exponential", "gamma", "bytes",
        "get_state", "set_state", "laplace", "lognormal", "vonmises",
    ))
# stdlib `random` module-level draws (the hidden global Random())
_PY_GLOBAL = tuple(
    "random." + f for f in (
        "seed", "random", "randint", "randrange", "uniform", "shuffle",
        "choice", "choices", "sample", "gauss", "normalvariate",
        "betavariate", "expovariate", "getrandbits", "triangular",
    ))
# constructors that are fine seeded, wrong unseeded (OS/time entropy)
_CONSTRUCTORS = ("numpy.random.RandomState", "numpy.random.default_rng",
                 "random.Random", "numpy.random.Philox",
                 "numpy.random.PCG64", "numpy.random.SeedSequence")
_TIME_SOURCES = ("time.time", "time.time_ns", "time.monotonic",
                 "time.perf_counter")
# explicitly-keyed RNG namespaces — `jax.random.uniform(key, ...)` and
# mxnet_tpu.random both thread keys and are exactly what MX003 wants
_KEYED_PREFIXES = ("jax.", "mxnet_tpu.random.")


@register
class RngDiscipline(Checker):
    """Raw np.random.* / random.* / time-seeded RNG in library code —
    outside the sanctioned fold_in sites this breaks the replayability
    contract (per-sample streams must be pure functions of
    (seed, epoch, index))."""

    code = "MX003"
    name = "rng-discipline"
    hint = ("draw from an explicitly-seeded generator (np.random."
            "RandomState(seed) / Generator(Philox(key=fold_in(...))), "
            "mxnet_tpu.random.next_key()); a sanctioned fold_in seeding "
            "site carries # mxlint: disable=MX003")

    def check(self, ctx):
        findings = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = astutil.call_name(node, ctx.aliases)
            if name is None or \
                    name.startswith(_KEYED_PREFIXES):
                continue
            what = None
            if astutil.matches(name, _NP_GLOBAL) or \
                    astutil.matches(name, _PY_GLOBAL):
                what = "global-state RNG draw %s()" % name
            elif astutil.matches(name, _CONSTRUCTORS):
                if self._entropy_seeded(node, ctx):
                    what = ("%s() seeded from OS/time entropy — "
                            "not replayable" % name)
            if what is None:
                continue
            qn = astutil.qualname(node, ctx.parents)
            findings.append(ctx.finding(
                node, self.code,
                "%s in %s" % (what, qn),
                hint=self.hint,
                symbol="%s:%s" % (qn, name)))
        return findings

    def _entropy_seeded(self, call, ctx):
        """Unseeded constructor, or one seeded from a time source."""
        args = list(call.args) + [k.value for k in call.keywords]
        if not args:
            return True
        for a in args:
            for sub in ast.walk(a):
                if isinstance(sub, ast.Call) and astutil.matches(
                        astutil.call_name(sub, ctx.aliases),
                        _TIME_SOURCES):
                    return True
        return False
