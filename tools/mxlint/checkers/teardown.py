"""MX006 — the PR 2/9 teardown contract.

Anything that starts a ``Thread``/``Process``/``Timer`` must leave a
deterministic way out: a class owning one (``self._thread = ...``)
must define a ``close()``/``_halt()``-style method whose teardown path
``join``s with a timeout (a join without a timeout is a hang waiting
for a wedged worker); a function-local thread must be joined with a
timeout in the same function.
"""
import ast

from .. import astutil
from ..engine import Checker, register

_THREADLIKE = ("threading.Thread", "Thread", "threading.Timer", "Timer",
               "multiprocessing.Process", "Process")
_TEARDOWN_NAMES = {"close", "_close", "_halt", "halt", "stop", "_stop",
                   "shutdown", "_shutdown", "join", "_join", "__exit__",
                   "terminate", "_terminate", "teardown", "_teardown",
                   "_drain", "flush", "release"}
_FUNCS = (ast.FunctionDef, ast.AsyncFunctionDef)


def _is_threadlike(call, ctx):
    return astutil.matches(astutil.call_name(call, ctx.aliases),
                           _THREADLIKE)


def _join_with_timeout(node):
    """A ``x.join(...)`` call carrying a timeout (positional or
    keyword), or a ``.cancel()`` (Timers)."""
    for sub in ast.walk(node):
        if not isinstance(sub, ast.Call) or \
                not isinstance(sub.func, ast.Attribute):
            continue
        if sub.func.attr == "cancel":
            return True
        if sub.func.attr == "join" and (
                sub.args or any(k.arg == "timeout"
                                for k in sub.keywords)):
            return True
    return False


@register
class UnjoinedThread(Checker):
    """A class that starts a Thread/Process without a close()/_halt()
    teardown that joins-with-timeout (or a local thread never joined) —
    leaked workers wedge interpreter exit and starve the next test."""

    code = "MX006"
    name = "unjoined-thread"
    hint = ("add a close()/_halt() that sets the stop flag and "
            "thread.join(timeout=...) (see io._ThreadedPrefetch"
            "Teardown); a deliberate daemon watchdog carries "
            "# mxlint: disable=MX006")

    def check(self, ctx):
        findings = []
        classes = {n.name: n for n in ast.walk(ctx.tree)
                   if isinstance(n, ast.ClassDef)}
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call) or \
                    not _is_threadlike(node, ctx):
                continue
            cls = astutil.enclosing(node, ctx.parents, (ast.ClassDef,))
            fn = astutil.enclosing(node, ctx.parents, _FUNCS)
            if cls is not None and self._stored_on_self(node, ctx):
                if not self._class_tears_down(cls, classes):
                    findings.append(ctx.finding(
                        node, self.code,
                        "class %r starts a %s but defines no "
                        "close()/_halt()-style teardown that joins "
                        "with a timeout"
                        % (cls.name,
                           astutil.call_name(node, ctx.aliases)),
                        hint=self.hint, symbol=cls.name))
            elif fn is not None:
                if not _join_with_timeout(fn):
                    qn = astutil.qualname(fn, ctx.parents)
                    findings.append(ctx.finding(
                        node, self.code,
                        "%s started in %r is never joined with a "
                        "timeout in that function"
                        % (astutil.call_name(node, ctx.aliases), qn),
                        hint=self.hint, symbol=qn))
        return findings

    def _stored_on_self(self, call, ctx):
        """The created thread lands on an instance attribute (directly,
        via an intermediate local that is later stored, or appended to
        a self-owned list)."""
        stmt = astutil.enclosing(
            call, ctx.parents,
            (ast.Assign, ast.AugAssign, ast.AnnAssign, ast.Expr))
        if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
            targets = stmt.targets if isinstance(stmt, ast.Assign) \
                else [stmt.target]
            names = []
            for t in targets:
                if isinstance(t, ast.Attribute) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id == "self":
                    return True
                if isinstance(t, ast.Name):
                    names.append(t.id)
            if names:
                fn = astutil.enclosing(call, ctx.parents, _FUNCS)
                if fn is not None:
                    for sub in ast.walk(fn):
                        if isinstance(sub, ast.Assign):
                            for t in sub.targets:
                                if isinstance(t, ast.Attribute) and \
                                        isinstance(sub.value, ast.Name) \
                                        and sub.value.id in names:
                                    return True
        if isinstance(stmt, ast.Expr) and \
                isinstance(stmt.value, ast.Call) and \
                isinstance(stmt.value.func, ast.Attribute) and \
                stmt.value.func.attr == "append":
            holder = stmt.value.func.value
            for sub in ast.walk(holder):
                if isinstance(sub, ast.Name) and sub.id == "self":
                    return True
        return False

    def _class_tears_down(self, cls, classes, _seen=None):
        """``cls`` (or a same-module base) defines a teardown-named
        method that joins with a timeout — directly or via a one-hop
        self-method call."""
        _seen = _seen or set()
        if cls.name in _seen:
            return False
        _seen.add(cls.name)
        methods = {m.name: m for m in cls.body if isinstance(m, _FUNCS)}
        # BFS from the teardown-named entry points through self-method
        # delegation (flush -> _raise_writer_error -> _join_writer)
        queue = [m for name, m in methods.items()
                 if name in _TEARDOWN_NAMES]
        visited = set()
        while queue:
            m = queue.pop()
            if m.name in visited:
                continue
            visited.add(m.name)
            if _join_with_timeout(m):
                return True
            for sub in ast.walk(m):
                if isinstance(sub, ast.Call) and \
                        isinstance(sub.func, ast.Attribute) and \
                        isinstance(sub.func.value, ast.Name) and \
                        sub.func.value.id == "self" and \
                        sub.func.attr in methods:
                    queue.append(methods[sub.func.attr])
        for base in cls.bases:
            name = base.id if isinstance(base, ast.Name) else \
                (base.attr if isinstance(base, ast.Attribute) else None)
            if name in classes and self._class_tears_down(
                    classes[name], classes, _seen):
                return True
        return False
