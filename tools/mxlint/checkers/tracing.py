"""MX001 (tracer host sync) and MX002 (collective placement).

Both walk the functions :func:`astutil.traced_functions` proves run
under a jax trace, with a parameter-derived taint pass marking the
values that are actually tracers there.  Trace-time Python on *static*
config (env flags, shapes, ``is None`` checks on closures) stays
silent — only operations on tainted values fire.
"""
import ast

from .. import astutil
from ..engine import Checker, register

# callables that force a device->host sync when handed a tracer
_SYNC_CALLS = ("numpy.asarray", "numpy.array", "np.asarray", "np.array",
               "jax.device_get", "device_get", "onp.asarray",
               "onp.array")
_SYNC_BUILTINS = ("float", "int", "bool", "complex")
_SYNC_METHODS = {"item", "tolist", "__float__", "__int__"}

_COLLECTIVES = ("lax.psum", "psum", "lax.pmean", "pmean",
                "lax.all_gather", "all_gather", "lax.psum_scatter",
                "psum_scatter", "lax.all_to_all", "all_to_all",
                "lax.ppermute", "ppermute", "lax.pmax", "pmax",
                "lax.pmin", "pmin", "lax.pshuffle")


@register
class TracerHostSync(Checker):
    """float()/.item()/np.asarray()/device_get on a traced value inside
    a jit/shard_map/scan-visible function — a silent per-step host sync
    (or a ConcretizationTypeError at best)."""

    code = "MX001"
    name = "tracer-host-sync"
    hint = ("keep the value on device (jnp ops / lax.cond), or move the "
            "host read outside the traced function; a trace-time "
            "constant read is fine — suppress with "
            "# mxlint: disable=MX001")

    def check(self, ctx):
        findings = []
        traced = astutil.traced_functions(ctx.tree, ctx.aliases,
                                          ctx.parents)
        for fn in traced:
            tainted = astutil.tainted_names(fn, ctx.aliases)
            body = fn.body if isinstance(fn.body, list) else [fn.body]
            for stmt in body:
                for node in ast.walk(stmt):
                    if not isinstance(node, ast.Call):
                        continue
                    # don't blame the enclosing fn for a *nested* def's
                    # body — that def is itself in `traced`
                    owner = astutil.enclosing(
                        node, ctx.parents,
                        (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.Lambda))
                    if owner is not fn:
                        continue
                    hit = self._sync_kind(node, ctx, tainted)
                    if hit:
                        qn = astutil.qualname(fn, ctx.parents)
                        findings.append(ctx.finding(
                            node, self.code,
                            "%s on a traced value inside traced "
                            "function %r forces a device sync"
                            % (hit, qn),
                            hint=self.hint,
                            symbol="%s:%s" % (qn, hit)))
        return findings

    def _sync_kind(self, call, ctx, tainted):
        name = astutil.call_name(call, ctx.aliases)
        args = list(call.args) + [k.value for k in call.keywords]
        if astutil.matches(name, _SYNC_BUILTINS) and args:
            if any(astutil.contains_taint(a, tainted, ctx.aliases)
                   for a in args):
                return "%s()" % name
            return None
        if astutil.matches(name, _SYNC_CALLS):
            if any(astutil.contains_taint(a, tainted, ctx.aliases)
                   for a in args):
                return name + "()"
            return None
        if isinstance(call.func, ast.Attribute) and \
                call.func.attr in _SYNC_METHODS:
            if astutil.contains_taint(call.func.value, tainted,
                                      ctx.aliases):
                return ".%s()" % call.func.attr
        return None


@register
class CollectivePlacement(Checker):
    """psum/all_gather/... under value-dependent Python control flow
    inside a traced function: each host traces its own branch, the
    collective rosters diverge, and the job deadlocks — the shape the
    PR 2/3 watchdogs only catch at runtime."""

    code = "MX002"
    name = "collective-placement"
    hint = ("hoist the collective out of the branch, or make the branch "
            "on-device (lax.cond keeps the collective in both traces); "
            "config-static branches can be suppressed with "
            "# mxlint: disable=MX002")

    def check(self, ctx):
        findings = []
        traced = astutil.traced_functions(ctx.tree, ctx.aliases,
                                          ctx.parents)
        for fn in traced:
            tainted = astutil.tainted_names(fn, ctx.aliases)
            body = fn.body if isinstance(fn.body, list) else [fn.body]
            for stmt in body:
                for node in ast.walk(stmt):
                    if not isinstance(node, ast.Call):
                        continue
                    name = astutil.call_name(node, ctx.aliases)
                    if not astutil.matches(name, _COLLECTIVES):
                        continue
                    branch = self._value_dependent_branch(
                        node, fn, ctx, tainted)
                    if branch is None:
                        continue
                    qn = astutil.qualname(fn, ctx.parents)
                    findings.append(ctx.finding(
                        node, self.code,
                        "collective %s at a value-dependent %s "
                        "(line %d) inside traced function %r — hosts "
                        "whose values differ trace different "
                        "collective rosters and deadlock"
                        % (name, branch.__class__.__name__.lower(),
                           branch.lineno, qn),
                        hint=self.hint,
                        symbol="%s:%s" % (qn, name)))
        return findings

    def _value_dependent_branch(self, call, fn, ctx, tainted):
        """Innermost enclosing if/while/for (within ``fn``) whose
        test/iterable depends on a traced value, else None."""
        cur = ctx.parents.get(call)
        while cur is not None and cur is not fn:
            if isinstance(cur, (ast.If, ast.While)):
                if astutil.contains_taint(cur.test, tainted,
                                          ctx.aliases):
                    return cur
            elif isinstance(cur, ast.For):
                if astutil.contains_taint(cur.iter, tainted,
                                          ctx.aliases):
                    return cur
            elif isinstance(cur, (ast.FunctionDef,
                                  ast.AsyncFunctionDef, ast.Lambda)):
                return None  # nested def: judged on its own
            cur = ctx.parents.get(cur)
        return None
