"""mxlint engine: checker registry, suppressions, baseline, runner.

A checker is a class with ``code``/``name``/``hint`` and either a
per-file ``check(file_ctx) -> [Finding]`` (subclass :class:`Checker`)
or a whole-project ``check_project(project_ctx) -> [Finding]``
(subclass :class:`ProjectChecker` — for cross-file registries like the
env-var catalog).  Register with ``@register``.

Suppressions: ``# mxlint: disable=MX001`` (or ``=MX001,MX003`` /
``=all``) on the finding's line, or ``# mxlint: disable-file=CODE``
within the first ten lines of the file.

Baseline: grandfathered findings live in ``tools/mxlint/baseline.json``
keyed by ``path::code::symbol`` (no line numbers, so unrelated edits
don't churn it) with an occurrence count.  ``--write-baseline``
regenerates it; ``--prune-baseline`` fails when an entry no longer
matches anything, so the debt can only shrink.
"""
import ast
import fnmatch
import json
import os
import re
import sys

JSON_SCHEMA_VERSION = 1
DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.json")

_SUPPRESS_RE = re.compile(
    r"#\s*mxlint:\s*disable=([A-Za-z0-9_,\s]+)")
_SUPPRESS_FILE_RE = re.compile(
    r"#\s*mxlint:\s*disable-file=([A-Za-z0-9_,\s]+)")

# directories never worth parsing
_SKIP_DIRS = {"__pycache__", ".git", "_build", ".ipynb_checkpoints",
              "node_modules"}


class Finding(object):
    """One diagnostic.

    ``symbol`` is the checker-chosen *stable identity* of the finding
    (an env-var name, a class name, a ``function:callee`` pair...) —
    the baseline keys on ``path::code::symbol`` so reformatting a file
    does not invalidate grandfathered entries.
    """

    __slots__ = ("path", "line", "col", "code", "message", "hint",
                 "symbol", "baselined")

    def __init__(self, path, line, col, code, message, hint="",
                 symbol=""):
        self.path = path
        self.line = int(line)
        self.col = int(col)
        self.code = code
        self.message = message
        self.hint = hint
        self.symbol = symbol or "%s:%s" % (line, col)
        self.baselined = False

    @property
    def key(self):
        return "%s::%s::%s" % (self.path, self.code, self.symbol)

    def render(self):
        txt = "%s:%d:%d: %s %s" % (
            self.path, self.line, self.col, self.code, self.message)
        if self.hint:
            txt += "\n    fix: %s" % self.hint
        return txt

    def as_json(self):
        return {"path": self.path, "line": self.line, "col": self.col,
                "code": self.code, "message": self.message,
                "hint": self.hint, "symbol": self.symbol,
                "baselined": self.baselined}


class FileContext(object):
    """Parsed view of one source file handed to per-file checkers."""

    def __init__(self, path, relpath, source, tree):
        self.path = path          # absolute
        self.relpath = relpath    # repo-root-relative, '/'-separated
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self._aliases = None
        self._parents = None

    @property
    def aliases(self):
        if self._aliases is None:
            from . import astutil
            self._aliases = astutil.import_aliases(self.tree)
        return self._aliases

    @property
    def parents(self):
        if self._parents is None:
            from . import astutil
            self._parents = astutil.parent_map(self.tree)
        return self._parents

    def finding(self, node, code, message, hint="", symbol=""):
        return Finding(self.relpath, getattr(node, "lineno", 1),
                       getattr(node, "col_offset", 0) + 1, code,
                       message, hint, symbol)


class ProjectContext(object):
    """Whole-repo view for cross-file checkers (MX004/MX005).

    ``files`` is the list of scanned FileContexts; ``root`` the repo
    root.  ``library_files()`` parses the *canonical* code set
    (mxnet_tpu/, tools/, bench*.py, __graft_entry__.py) even when the
    CLI was pointed at a subset, so registry comparisons are stable.
    """

    def __init__(self, root, files):
        self.root = root
        self.files = files
        self._canon = None

    def read(self, relpath):
        p = os.path.join(self.root, relpath)
        if not os.path.exists(p):
            return None
        with open(p, "r", encoding="utf-8", errors="replace") as f:
            return f.read()

    def library_files(self):
        if self._canon is not None:
            return self._canon
        canon_rel = set()
        for sub in ("mxnet_tpu", "tools"):
            base = os.path.join(self.root, sub)
            if os.path.isdir(base):
                for p in _iter_py(base):
                    canon_rel.add(os.path.relpath(p, self.root))
        for name in sorted(os.listdir(self.root)):
            if fnmatch.fnmatch(name, "bench*.py") or \
                    name == "__graft_entry__.py":
                canon_rel.add(name)
        by_rel = {f.relpath: f for f in self.files}
        out = []
        for rel in sorted(r.replace(os.sep, "/") for r in canon_rel):
            if rel in by_rel:
                out.append(by_rel[rel])
                continue
            parsed = _parse_file(os.path.join(self.root, rel), rel)
            if isinstance(parsed, FileContext):
                out.append(parsed)
        self._canon = out
        return out


class Checker(object):
    code = "MX000"
    name = "unnamed"
    hint = ""

    def check(self, ctx):  # pragma: no cover - interface
        raise NotImplementedError


class ProjectChecker(Checker):
    def check_project(self, project):  # pragma: no cover - interface
        raise NotImplementedError


_REGISTRY = {}


def register(cls):
    """Class decorator: add a checker to the global registry."""
    if cls.code in _REGISTRY and _REGISTRY[cls.code] is not cls:
        raise ValueError("duplicate checker code %s" % cls.code)
    _REGISTRY[cls.code] = cls
    return cls


def all_checkers():
    from . import checkers  # noqa: F401 — populates the registry
    return dict(_REGISTRY)


# ---------------------------------------------------------------------------
# file discovery / parsing


def _iter_py(path):
    if os.path.isfile(path):
        yield path
        return
    for dirpath, dirnames, filenames in os.walk(path):
        dirnames[:] = sorted(d for d in dirnames if d not in _SKIP_DIRS)
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def _parse_file(path, relpath):
    """FileContext, or a Finding (MX000) on unreadable/unparsable."""
    try:
        with open(path, "r", encoding="utf-8", errors="replace") as f:
            source = f.read()
        tree = ast.parse(source, filename=path)
    except (OSError, SyntaxError, ValueError) as exc:
        line = getattr(exc, "lineno", 1) or 1
        return Finding(relpath, line, 1, "MX000",
                       "cannot parse: %s" % exc,
                       symbol="parse-error")
    return FileContext(path, relpath, source, tree)


def find_root(start):
    """Ascend from ``start`` to the repo root (the dir holding
    docs/env_vars.md or .git); fall back to ``start``."""
    cur = os.path.abspath(start)
    if os.path.isfile(cur):
        cur = os.path.dirname(cur)
    while True:
        if os.path.exists(os.path.join(cur, "docs", "env_vars.md")) or \
                os.path.isdir(os.path.join(cur, ".git")):
            return cur
        parent = os.path.dirname(cur)
        if parent == cur:
            return os.path.abspath(start if os.path.isdir(start)
                                   else os.path.dirname(start))
        cur = parent


# ---------------------------------------------------------------------------
# suppressions


def suppressed_codes(ctx):
    """{lineno: set(codes)} plus a '*'-keyed file-wide set.

    A suppression on a comment-only line also covers the next code
    line (so long hints fit above the statement they wave through).
    """
    per_line = {}
    for i, text in enumerate(ctx.lines, 1):
        m = _SUPPRESS_RE.search(text)
        if m:
            codes = {c.strip().upper() for c in m.group(1).split(",")
                     if c.strip()}
            per_line.setdefault(i, set()).update(codes)
            if text.lstrip().startswith("#"):
                j = i + 1
                while j <= len(ctx.lines) and \
                        (not ctx.lines[j - 1].strip() or
                         ctx.lines[j - 1].lstrip().startswith("#")):
                    j += 1
                per_line.setdefault(j, set()).update(codes)
        if i <= 10:
            mf = _SUPPRESS_FILE_RE.search(text)
            if mf:
                codes = {c.strip().upper() for c in mf.group(1).split(",")
                         if c.strip()}
                per_line.setdefault("*", set()).update(codes)
    return per_line


def _is_suppressed(finding, supp_by_file):
    supp = supp_by_file.get(finding.path)
    if not supp:
        return False
    filewide = supp.get("*", set())
    if "ALL" in filewide or finding.code in filewide:
        return True
    codes = supp.get(finding.line, set())
    return "ALL" in codes or finding.code in codes


# ---------------------------------------------------------------------------
# baseline


def load_baseline(path):
    if not path or not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    entries = data.get("entries", {})
    return {str(k): int(v) for k, v in entries.items()}


def write_baseline(path, findings):
    entries = {}
    for f in findings:
        entries[f.key] = entries.get(f.key, 0) + 1
    payload = {
        "comment": "mxlint grandfathered findings — see "
                   "docs/static_analysis.md. Keys are path::code::symbol "
                   "with an occurrence count; --prune-baseline enforces "
                   "that this file only ever shrinks.",
        "version": 1,
        "entries": {k: entries[k] for k in sorted(entries)},
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=1, sort_keys=False)
        f.write("\n")
    return payload


def apply_baseline(findings, baseline):
    """Mark findings covered by the baseline; return the stale entries
    (key -> unmatched count) whose grandfathered debt no longer
    exists."""
    budget = dict(baseline)
    for f in findings:
        if budget.get(f.key, 0) > 0:
            budget[f.key] -= 1
            f.baselined = True
    return {k: v for k, v in budget.items() if v > 0}


# ---------------------------------------------------------------------------
# runner


def run_paths(paths, root=None, select=None, ignore=None):
    """Run every registered checker over ``paths``.

    Returns ``(findings, parse_errors)`` — suppression comments already
    applied (suppressed findings dropped), baseline NOT applied (the
    CLI layer owns that policy).
    """
    checkers = all_checkers()
    if select:
        checkers = {c: v for c, v in checkers.items() if c in select}
    if ignore:
        checkers = {c: v for c, v in checkers.items() if c not in ignore}

    root = os.path.abspath(root or find_root(paths[0] if paths else "."))
    files, parse_errors = [], []
    seen = set()
    for p in paths:
        for fp in _iter_py(os.path.abspath(p)):
            if fp in seen:
                continue
            seen.add(fp)
            rel = os.path.relpath(fp, root).replace(os.sep, "/")
            parsed = _parse_file(fp, rel)
            if isinstance(parsed, Finding):
                parse_errors.append(parsed)
            else:
                files.append(parsed)

    project = ProjectContext(root, files)
    findings = []
    instances = [cls() for _, cls in sorted(checkers.items())]
    for ctx in files:
        for chk in instances:
            if isinstance(chk, ProjectChecker):
                continue
            findings.extend(chk.check(ctx))
    for chk in instances:
        if isinstance(chk, ProjectChecker):
            findings.extend(chk.check_project(project))

    supp_by_file = {ctx.relpath: suppressed_codes(ctx) for ctx in files}
    findings = [f for f in findings
                if not _is_suppressed(f, supp_by_file)]
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.code))
    return findings, parse_errors


def emit_json(findings, parse_errors, stale, stream=None):
    """The stable ``--json`` artifact (schema version pinned by
    tests/test_mxlint.py)."""
    active = [f for f in findings if not f.baselined]
    payload = {
        "kind": "mxnet_tpu-mxlint",
        "schema_version": JSON_SCHEMA_VERSION,
        "counts": {
            "findings": len(active),
            "baselined": sum(1 for f in findings if f.baselined),
            "parse_errors": len(parse_errors),
            "stale_baseline": len(stale),
        },
        "findings": [f.as_json() for f in findings],
        "parse_errors": [f.as_json() for f in parse_errors],
        "stale_baseline": sorted(stale),
    }
    json.dump(payload, stream or sys.stdout, indent=1)
    (stream or sys.stdout).write("\n")
    return payload
