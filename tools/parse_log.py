#!/usr/bin/env python
"""parse_log.py — extract per-epoch metrics from training logs
(reference ``tools/parse_log.py``: turns Module.fit/Speedometer output
into a table).

Usage: python tools/parse_log.py logfile [--format markdown|csv]
"""
from __future__ import annotations

import argparse
import re
import sys
from collections import defaultdict

# Epoch[3] Train-accuracy=0.912345   /  Epoch[3] Validation-accuracy=...
_METRIC = re.compile(
    r"Epoch\[(\d+)\]\s+(Train|Validation)-([\w-]+)=([\d.eE+-]+)")
# Epoch[3] Time cost=12.345
_TIME = re.compile(r"Epoch\[(\d+)\]\s+Time cost=([\d.eE+-]+)")
# Speedometer: Epoch[3] Batch [40]  Speed: 123.45 samples/sec
_SPEED = re.compile(r"Epoch\[(\d+)\].*Speed[:=]\s*([\d.eE+-]+)")


def parse(lines):
    rows = defaultdict(dict)
    speeds = defaultdict(list)
    for line in lines:
        m = _METRIC.search(line)
        if m:
            epoch, phase, name, val = m.groups()
            rows[int(epoch)]["%s-%s" % (phase.lower(), name)] = float(val)
            continue
        m = _TIME.search(line)
        if m:
            rows[int(m.group(1))]["time"] = float(m.group(2))
            continue
        m = _SPEED.search(line)
        if m:
            speeds[int(m.group(1))].append(float(m.group(2)))
    for epoch, vals in speeds.items():
        rows[epoch]["speed"] = sum(vals) / len(vals)
    return dict(rows)


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("logfile")
    ap.add_argument("--format", choices=("markdown", "csv"),
                    default="markdown")
    args = ap.parse_args()
    with open(args.logfile) as f:
        rows = parse(f)
    if not rows:
        print("no epochs found", file=sys.stderr)
        return
    cols = sorted({k for v in rows.values() for k in v})
    if args.format == "csv":
        print(",".join(["epoch"] + cols))
        for e in sorted(rows):
            print(",".join([str(e)] + [str(rows[e].get(c, ""))
                                       for c in cols]))
    else:
        print("| epoch | " + " | ".join(cols) + " |")
        print("|" + "---|" * (len(cols) + 1))
        for e in sorted(rows):
            print("| %d | " % e + " | ".join(
                "%.6g" % rows[e][c] if c in rows[e] else ""
                for c in cols) + " |")


if __name__ == "__main__":
    main()
